"""Platform assembly: one object wiring API server + controllers + services.

The deployment plane's engine (SURVEY.md §7.4): what the reference reaches
through the vendored kfctl coordinator (bootstrap/cmd/bootstrap/app/
kfctlServer.go:105-312 — load KfDef, Apply(PLATFORM), Apply(K8S) with
retries) becomes an explicit, testable object: apply a PlatformConfig,
components come up; apply again, nothing changes (the second-apply
idempotency contract, reference testing/kfctl/kfctl_second_apply.py:12-24).

State is persisted as a YAML resource dump so ``tpuctl`` invocations
compose across processes without a running cluster.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import yaml

from kubeflow_tpu.controlplane.api import object_from_dict, to_dict
from kubeflow_tpu.controlplane.api.types import PlatformConfig
from kubeflow_tpu.controlplane.controllers import (
    FakeKubelet,
    NotebookController,
    PodDefaultMutator,
    ProfileController,
    ServingController,
    StudyJobController,
    TensorboardController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.kfam import AccessManagement
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.obs.goodput import GOODPUT_STATE
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer

log = get_logger("platform")

TRACE_FILE = "trace.jsonl"

DEFAULT_COMPONENTS = (
    "tpujob-controller",
    "studyjob-controller",   # HPO (katib equivalent); trials are TpuJobs
    "notebook-controller",
    "profile-controller",
    "tensorboard-controller",
    "serving-controller",    # inference deployments (TF-Serving equivalent)
    "serving-autoscaler",    # latency-driven replica scaling for Servings
    "poddefault-webhook",
    "kfam",
    "jupyter-web-app",       # L3 spawner REST backend
    "centraldashboard",      # L3 workgroup API (requires kfam)
    "fake-kubelet",          # local/dev compute double; real clusters disable
    "availability-prober",   # platform SLO gauge (metric-collector equiv)
)

# Start order: kfam before centraldashboard (the dashboard wraps it),
# regardless of the order components appear in the config.
_START_ORDER = {name: i for i, name in enumerate(DEFAULT_COMPONENTS)}


class Platform:
    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 workers: Optional[int] = None):
        self.registry = registry or MetricsRegistry()
        # Per-platform tracer + registry on the apiserver and the manager
        # (not the process-global ones): `tpuctl metrics` renders THIS
        # registry, so the verb/reconcile histograms must land here, and
        # two Platforms in one process must not interleave their traces.
        self.tracer = tracer or Tracer()
        self.api = InMemoryApiServer(registry=self.registry,
                                     tracer=self.tracer)
        # ``workers`` sizes the manager's reconcile pool (default 1 =
        # serial dispatch; per-key serialization holds at any size, so
        # tpuctl --wait's run_until_idle drain stays deterministic).
        # ``KFTPU_WORKERS`` overrides the default so every Platform
        # entrypoint (tpuctl, bootstrap, CI) can run pooled without
        # threading a flag through each subcommand.
        if workers is None:
            raw = os.environ.get("KFTPU_WORKERS", "1") or "1"
            try:
                workers = int(raw)
            except ValueError:
                raise ValueError(
                    f"KFTPU_WORKERS must be an integer >= 1, got {raw!r}"
                ) from None
        self.manager = ControllerManager(self.api, self.registry,
                                         tracer=self.tracer,
                                         workers=workers)
        self.kfam: Optional[AccessManagement] = None
        self.scheduler = None    # GangScheduler when a fleet is configured
        self.goodput = None      # GoodputAccountant when capacity is known
        self.slo = None          # SLOEngine (ISSUE 15)
        self.flight = None       # FlightRecorder (ISSUE 15)
        self.remediate = None    # RemediationController (ISSUE 17)
        self.jwa = None          # NotebookWebApp when enabled
        self.dashboard = None    # DashboardApi when enabled
        self.prober = None       # AvailabilityProber when enabled
        self.wal = None          # WriteAheadLog when attached
        self.components: List[str] = []
        self._config: Optional[PlatformConfig] = None
        # Known only on the load() path: where the alert journal,
        # flight dumps, and other durable observability artifacts live.
        self._state_dir = ""

    def attach_wal(self, state_dir: str, *, fsync: bool = True):
        """Journal every committed API write to ``<state_dir>/wal.jsonl``
        (fsync'd per record, before the write's watch event is visible) so
        a crash between ``save()`` calls replays to its exact pre-crash
        state. ``save()`` compacts the log behind the snapshot it wrote."""
        from kubeflow_tpu.controlplane.wal import WriteAheadLog, wal_path

        os.makedirs(state_dir, exist_ok=True)
        self.wal = WriteAheadLog(wal_path(state_dir), fsync=fsync)
        self.wal.attach(self.api)
        return self.wal

    # ------------- component wiring -------------

    def apply_config(self, cfg: PlatformConfig) -> List[str]:
        """Bring up the components the config enables. Idempotent: already-
        running components are left alone. When the spec carries a
        ``substrate`` section, the provider half (Apply(PLATFORM)) runs
        FIRST — slice/node pools exist before any component starts — and
        the config is finalizer-guarded so delete must reclaim them."""
        self._config = cfg
        from kubeflow_tpu.controlplane.substrate import (
            SUBSTRATE_FINALIZER,
            deprovision_checked,
            provision,
        )

        prior = self.api.try_get("PlatformConfig", cfg.metadata.name)
        prior_sub = prior.spec.substrate if prior is not None else None
        new_sub = cfg.spec.substrate
        if new_sub is not None and new_sub.provider:
            # DRY-validate the new substrate FIRST: a provider switch
            # must never destroy healthy pools for a config that could
            # not have provisioned anyway.
            from kubeflow_tpu.controlplane.substrate import get_provider

            get_provider(new_sub.provider).validate_spec(new_sub)
        if prior_sub is not None and prior_sub.provider and (
                new_sub is None or prior_sub.provider != new_sub.provider):
            # The re-applied spec dropped (or switched) its substrate:
            # reclaim the old provider's pools NOW, leak-checked —
            # otherwise they orphan with no spec left pointing at them.
            deprovision_checked(cfg.metadata.name, prior_sub)
        if new_sub is not None and new_sub.provider:
            provision(cfg.metadata.name, new_sub)
            if SUBSTRATE_FINALIZER not in cfg.metadata.finalizers:
                cfg.metadata.finalizers.append(SUBSTRATE_FINALIZER)
        elif SUBSTRATE_FINALIZER in cfg.metadata.finalizers:
            cfg.metadata.finalizers.remove(SUBSTRATE_FINALIZER)
        wanted = [
            c.name for c in cfg.spec.components if c.enabled
        ] or list(DEFAULT_COMPONENTS)
        wanted.sort(key=lambda n: _START_ORDER.get(n, len(_START_ORDER)))
        params: Dict[str, Dict[str, str]] = {
            c.name: dict(c.params) for c in cfg.spec.components
        }
        started = []
        for name in wanted:
            if name in self.components:
                continue
            self._start_component(name, cfg, params.get(name, {}))
            self.components.append(name)
            started.append(name)
        cfg.status.phase = "Ready"
        cfg.status.applied_components = list(self.components)
        existing = self.api.try_get("PlatformConfig", cfg.metadata.name)
        if existing is None:
            self.api.create(cfg)
        elif (existing.spec != cfg.spec or existing.status != cfg.status
              or existing.metadata.finalizers != cfg.metadata.finalizers):
            # Second-apply idempotency contract (reference
            # testing/kfctl/kfctl_second_apply.py:12-24): an apply that
            # changes nothing must not bump any resourceVersion. The
            # finalizer list IS part of what an apply may change (the
            # substrate guard must persist on the STORED config).
            existing.spec = cfg.spec
            existing.status = cfg.status
            existing.metadata.finalizers = list(cfg.metadata.finalizers)
            self.api.update(existing)
        return started

    def _start_component(self, name: str, cfg: PlatformConfig,
                         params: Dict[str, str]) -> None:
        reg = self.registry
        if name == "tpujob-controller":
            capacity = None
            if "capacity" in params:
                capacity = {
                    k: int(v) for k, v in (
                        kv.split("=") for kv in params["capacity"].split(",")
                    )
                }
            scheduler = None
            if "fleet" in params:
                # Topology-aware gang scheduler (ISSUE 8): a fleet spec
                # like "v5e-16=8,v5e-32=4" builds slice pools with DCN
                # adjacency; the scheduler then owns slice_assignment
                # for those types and a DefragController consolidates
                # free slices in the background.
                from kubeflow_tpu.scheduler import (
                    DefragController,
                    Fleet,
                    GangScheduler,
                )

                fleet_cap = {
                    k: int(v) for k, v in (
                        kv.split("=") for kv in params["fleet"].split(",")
                    )
                }
                fleet = Fleet.from_capacity(
                    fleet_cap,
                    pool_size=int(params.get("poolSize", 8)))
                scheduler = GangScheduler(
                    fleet, registry=reg, tracer=self.tracer,
                    policy=params.get("schedulerPolicy", "priority"))
                self.scheduler = scheduler
                if params.get("defrag", "true") != "false":
                    self.manager.register(DefragController(
                        self.api, reg, scheduler=scheduler,
                        tracer=self.tracer,
                        threshold=float(params.get("defragThreshold", 0.5)),
                        interval_s=float(
                            params.get("defragIntervalSeconds", 30)),
                    ))
                if params.get("elastic", "true") != "false":
                    # Elastic gangs (ISSUE 11): grows under-sized
                    # elastic TpuJobs back toward max_slices when the
                    # fleet frees units (the shrink half lives in the
                    # TpuJobController's resize branch).
                    from kubeflow_tpu.elastic import ElasticController

                    self.manager.register(ElasticController(
                        self.api, reg, scheduler=scheduler,
                        tracer=self.tracer,
                        interval_s=float(
                            params.get("elasticIntervalSeconds", 15)),
                    ))
            self.manager.register(TpuJobController(self.api, reg,
                                                   capacity=capacity,
                                                   scheduler=scheduler))
            # Fleet goodput ledger (ISSUE 10): tracked whenever the
            # platform knows its offered capacity (a scheduler fleet's
            # concrete units, else the capacity map's synthetic slots).
            # Live runs attribute monotonic nanoseconds; conservation
            # stays integer-exact. Surfaced by `tpuctl goodput`.
            from kubeflow_tpu.obs.goodput import GoodputAccountant

            if scheduler is not None:
                self.goodput = GoodputAccountant.from_fleet(
                    scheduler.fleet, registry=reg, tick_seconds=1e-9)
            elif capacity:
                self.goodput = GoodputAccountant.from_capacity(
                    capacity, registry=reg, tick_seconds=1e-9)
            if self.goodput is not None:
                self.goodput.attach(self.api)
                self.goodput.reset_clock(time.monotonic_ns())
            # SLO engine + flight recorder (ISSUE 15): the
            # detect-and-explain layer over everything the registry
            # records. Real-time windows (evaluated per reconcile()
            # pass with a monotonic clock); the alert journal and
            # flight dumps live under the state dir when one is known
            # (the tpuctl load path).
            from kubeflow_tpu.obs.flight import FlightRecorder
            from kubeflow_tpu.obs.remediate import (
                ACTIONS_JOURNAL,
                RemediationController,
                remediation_objective,
                requeue_playbook,
            )
            from kubeflow_tpu.obs.slo import (
                ALERTS_JOURNAL,
                DEFAULT_WINDOWS,
                SLOEngine,
                default_objectives,
            )

            self.flight = FlightRecorder(tracer=self.tracer,
                                         registry=reg)
            self.flight.attach(self.api)
            self.slo = SLOEngine(
                reg,
                objectives=default_objectives(goodput=self.goodput)
                + [remediation_objective(windows=DEFAULT_WINDOWS,
                                         clear_after=3)],
                recorder=self.flight,
                dump_dir=self._state_dir,
            )
            if self.goodput is not None:
                acc = self.goodput
                self.slo.add_guard(
                    "goodput-conservation",
                    lambda: acc.conservation()["exact"])
            # Remediation controller (ISSUE 17): closes the loop from
            # SLO page to a budgeted, journaled action. The live
            # platform's one in-process seam is the park-path requeue;
            # cadences are real seconds to match DEFAULT_WINDOWS burn
            # decay. Operators inspect/override via `tpuctl remediate`.
            self.remediate = RemediationController(
                reg,
                engine=self.slo,
                playbooks=[requeue_playbook(
                    self.manager, budget=3, cooldown=60.0,
                    verify_after=300.0)],
                recorder=self.flight,
                dump_dir=self._state_dir,
                accountant=self.goodput,
            )
            if self._state_dir:
                # The dir may not exist yet (first apply): the journals
                # append lazily, but their directory must be there
                # before the first alert fires, not first save().
                os.makedirs(self._state_dir, exist_ok=True)
                self.slo.set_journal(
                    os.path.join(self._state_dir, ALERTS_JOURNAL))
                self.remediate.set_journal(
                    os.path.join(self._state_dir, ACTIONS_JOURNAL))
        elif name == "studyjob-controller":
            self.manager.register(StudyJobController(self.api, reg))
        elif name == "notebook-controller":
            probe = None
            if params.get("activityProbe", "") == "http":
                probe = NotebookController.http_activity_probe()
            self.manager.register(NotebookController(
                self.api, reg,
                enable_culling=params.get("enableCulling", "") == "true",
                idle_seconds=float(params.get("idleSeconds", 1440 * 60)),
                istio_gateway=cfg.spec.istio_gateway,
                activity_probe=probe,
            ))
        elif name == "profile-controller":
            self.manager.register(ProfileController(
                self.api, reg, user_id_header=cfg.spec.user_id_header,
            ))
        elif name == "tensorboard-controller":
            self.manager.register(TensorboardController(
                self.api, reg, istio_gateway=cfg.spec.istio_gateway,
            ))
        elif name == "serving-controller":
            self.manager.register(ServingController(
                self.api, reg, istio_gateway=cfg.spec.istio_gateway,
            ))
        elif name == "serving-autoscaler":
            from kubeflow_tpu.controlplane.controllers import (
                ServingAutoscaler,
            )

            # The platform's own tracer so autoscale.scrape/decision spans
            # land next to the reconcile spans `tpuctl trace` renders.
            self.manager.register(ServingAutoscaler(
                self.api, reg, tracer=self.tracer,
                interval_s=float(params.get("intervalSeconds", 10)),
                scale_down_stabilization_s=float(
                    params.get("scaleDownStabilizationSeconds", 60)),
            ))
        elif name == "poddefault-webhook":
            self.api.register_mutator(PodDefaultMutator(self.api))
        elif name == "kfam":
            self.kfam = AccessManagement(
                self.api, reg, user_id_header=cfg.spec.user_id_header,
                default_chip_quota=int(params.get("defaultChipQuota", 0)),
            )
        elif name == "jupyter-web-app":
            from kubeflow_tpu.webapps.jwa import NotebookWebApp

            self.jwa = NotebookWebApp(
                self.api, reg, user_id_header=cfg.spec.user_id_header,
            )
        elif name == "centraldashboard":
            from kubeflow_tpu.webapps.dashboard import DashboardApi

            if self.kfam is None:
                raise ValueError(
                    "centraldashboard requires the kfam component"
                )
            self.dashboard = DashboardApi(self.kfam)
        elif name == "fake-kubelet":
            self.manager.register(FakeKubelet(self.api, reg))
        elif name == "availability-prober":
            from kubeflow_tpu.controlplane.prober import (
                AvailabilityProber,
                controller_target,
                http_target,
            )

            # Started last (component order). Controller targets are real
            # liveness checks (fresh heartbeat OR idle manager — a stale
            # heartbeat with work queued = wedged loop); in-process services
            # probe presence; params["urls"] adds HTTP /healthz routes.
            max_age = float(params.get("heartbeatMaxAgeSeconds", 120))
            targets = {
                ctl.NAME: controller_target(self.manager, ctl, max_age)
                for ctl in self.manager.controllers
            }
            for svc_name, getter in (
                ("kfam", lambda: self.kfam),
                ("jupyter-web-app", lambda: self.jwa),
                ("centraldashboard", lambda: self.dashboard),
            ):
                if getter() is not None:
                    targets[svc_name] = (
                        lambda g=getter: g() is not None
                    )
            for url in filter(None, params.get("urls", "").split(",")):
                targets[url.split("//")[-1].replace("/", "_")] = (
                    http_target(url.strip())
                )
            self.prober = AvailabilityProber(
                targets, reg,
                interval_s=float(params.get("intervalSeconds", 30)),
            )
            self.prober.probe()
        else:
            raise ValueError(f"unknown component {name!r}")
        log.info("component started", kv={"component": name})

    # ------------- resource apply -------------

    def apply_resource(self, data: dict):
        """kubectl-apply semantics for one manifest dict."""
        obj = object_from_dict(data)
        if obj.kind == "PlatformConfig":
            self.apply_config(obj)
            return obj
        existing = self.api.try_get(
            obj.kind, obj.metadata.name, obj.metadata.namespace
        )
        if existing is None:
            return self.api.create(obj)
        if getattr(obj, "spec", None) is not None and existing.spec != obj.spec:
            existing.spec = obj.spec
            return self.api.update(existing)
        return existing

    def reconcile(self) -> int:
        n = self.manager.run_until_idle(include_timers_within=0.2)
        if self.prober is not None:
            self.prober.maybe_probe()
        # Tenant tree (ISSUE 13): rebuilt from live Profiles each pass —
        # the scheduler's weighted-DRF decisions and the goodput
        # ledger's tenant rollup (journaled "tn" records) both follow
        # the org chart as it is NOW. No Profiles = tenant-blind, the
        # pre-ISSUE-13 behaviour.
        if self.goodput is not None or self.scheduler is not None:
            profiles = self.api.list("Profile", copy=False)
            # Rebuild only when a Profile actually changed (resource
            # versions are the change signal): the tree is O(P log P)
            # to build and tenancy targets thousands of tenants — the
            # hot control loop must not pay that per pass.
            key = tuple(sorted(
                (p.metadata.name, p.metadata.resource_version)
                for p in profiles))
            if key != getattr(self, "_tenant_tree_key", object()):
                self._tenant_tree_key = key
                tree = None
                if profiles:
                    from kubeflow_tpu.tenancy import TenantTree

                    tree = TenantTree.from_profiles(profiles)
                # tree may be None: deleting the last Profile DETACHES
                # the market — a stale org chart must not keep
                # enforcing DRF or attributing tenants after the
                # operator turned tenancy off.
                if self.goodput is not None:
                    self.goodput.set_tenants(tree)
                if self.scheduler is not None:
                    self.scheduler.tenants = tree
        if self.goodput is not None:
            self.goodput.pump()
            self.goodput.tick(time.monotonic_ns())
        # SLO evaluation rides every reconcile pass: fold fresh watch
        # events into the flight ring, note metric movement, then run
        # the burn-rate state machine (which journals transitions and
        # dumps the ring on a page or a tripped guard).
        if self.flight is not None:
            self.flight.pump()
            self.flight.record_metric_deltas()
        if self.slo is not None:
            fired = self.slo.evaluate(time.monotonic())
            if self.remediate is not None and self.remediate.tick(
                    time.monotonic(), fired=fired):
                # An action ran (requeue fills the workqueue): drain it
                # in THIS pass so the remediation's effect is visible to
                # the caller's convergence checks, not the next one's.
                n += self.manager.run_until_idle(
                    include_timers_within=0.2)
        return n

    def substrate_spec(self, name: str):
        """The deployment's effective substrate spec: the STORED config
        wins, falling back to the in-memory applied config — a failed
        apply may have provisioned pools before the config ever reached
        the store, and both delete and the operator inspection endpoint
        must see them."""
        cfg = self.api.try_get("PlatformConfig", name)
        if cfg is not None:
            return cfg.spec.substrate
        return (self._config.spec.substrate
                if self._config is not None else None)

    def delete_config(self, name: str) -> List[str]:
        """Tear the deployment's substrate down (finalizer-guarded) and
        delete the PlatformConfig. Deprovision is leak-checked: anything
        the provider still tracks afterwards raises instead of silently
        surviving (reference kfctl_delete_test.py:44-71). Returns the
        reclaimed pool names."""
        from kubeflow_tpu.controlplane.substrate import (
            SUBSTRATE_FINALIZER,
            deprovision_checked,
        )

        cfg = self.api.try_get("PlatformConfig", name)
        deleted = deprovision_checked(name, self.substrate_spec(name))
        if cfg is not None:
            if SUBSTRATE_FINALIZER in cfg.metadata.finalizers:
                cfg.metadata.finalizers.remove(SUBSTRATE_FINALIZER)
                self.api.update(cfg)
            self.api.delete("PlatformConfig", name)
        return deleted

    # ------------- persistence -------------

    def save(self, state_dir: str) -> str:
        os.makedirs(state_dir, exist_ok=True)
        path = os.path.join(state_dir, "state.yaml")
        # Capture the object set and the rv counter under the store lock:
        # they must be ATOMIC with each other, or a write committing
        # between them lands inside saved_rv yet outside the snapshot —
        # and wal.compact(saved_rv) below would then delete its journal
        # record too, losing the write entirely. Serialization stays
        # outside the lock (stored objects are immutable snapshots, so
        # the captured references cannot change under us).
        with self.api._lock:
            objs = [self.api._objects[key]
                    for key in sorted(self.api._objects)]
            saved_rv = self.api._rv
        docs = [to_dict(obj) for obj in objs]
        meta = {
            "kind": "PlatformState",
            "components": self.components,
            "resourceVersionCounter": saved_rv,
        }
        # Write-to-temp + atomic rename: a kill mid-dump used to leave a
        # truncated state.yaml — the next load would come up EMPTY and a
        # subsequent save would bury the loss. os.replace is atomic on
        # POSIX, so readers only ever see the old or the new snapshot.
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            yaml.safe_dump_all([meta] + docs, f, sort_keys=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if self.wal is not None:
            # The snapshot covers everything up to saved_rv: compact the
            # WAL down to the (normally empty) newer tail.
            self.wal.compact(saved_rv)
        # Append spans recorded since the last save so `tpuctl trace` can
        # reconstruct causal timelines across tpuctl invocations; past
        # the byte cap the file rolls to trace.jsonl.1 (single
        # generation — the ring is bounded, the state dir must be too)
        # and `tpuctl trace` reads both generations.
        trace_path = os.path.join(state_dir, TRACE_FILE)
        self.tracer.export_new_jsonl(trace_path)
        self.tracer.rotate_jsonl(trace_path)
        if self.goodput is not None:
            # Goodput ledger totals persist across tpuctl invocations
            # (integer tallies — the time BETWEEN processes is not
            # platform time and is deliberately not counted).
            with open(os.path.join(state_dir, GOODPUT_STATE + ".tmp"),
                      "w") as f:
                json.dump(self.goodput.dump_state(), f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(os.path.join(state_dir, GOODPUT_STATE + ".tmp"),
                       os.path.join(state_dir, GOODPUT_STATE))
        return path

    @classmethod
    def load(cls, state_dir: str) -> "Platform":
        from kubeflow_tpu.controlplane.wal import wal_path

        path = os.path.join(state_dir, "state.yaml")
        platform = cls()
        # Components started below (apply_config) anchor their durable
        # observability artifacts — alerts.jsonl, flight dumps — here.
        platform._state_dir = state_dir
        has_wal = os.path.exists(wal_path(state_dir))
        if os.path.exists(path):
            with open(path) as f:
                docs = list(yaml.safe_load_all(f))
            if docs:
                meta, resources = docs[0], docs[1:]
                # Restore resources first (no mutators registered yet:
                # stored pods were already mutated at original create
                # time).
                for data in resources:
                    platform.api.load_snapshot(object_from_dict(data))
                platform.api._rv = int(
                    meta.get("resourceVersionCounter", 0))
        elif not has_wal:
            return platform
        if has_wal:
            # WAL replay is PREFERRED over the snapshot when both exist:
            # the log carries every fsync'd write since the snapshot was
            # taken (a crash between saves), so the replayed tail — not
            # the snapshot — is the true latest state. Attaching keeps
            # journaling subsequent writes, and the next save() compacts.
            wal = platform.attach_wal(state_dir)
            replayed = wal.replay(platform.api)
            if replayed:
                log.info("wal replayed", kv={
                    "records": replayed, "rv": platform.api._rv,
                })
        # Re-start components per stored PlatformConfig.
        pcs = platform.api.list("PlatformConfig")
        if pcs:
            platform.apply_config(pcs[0])
        gp_path = os.path.join(state_dir, GOODPUT_STATE)
        if platform.goodput is not None and os.path.exists(gp_path):
            # Resume the goodput ledger's integer tallies; the clock
            # baseline was just reset, so inter-invocation wall time
            # contributes nothing.
            with open(gp_path) as f:
                platform.goodput.load_state(json.load(f))
            if platform.slo is not None:
                # The interruption-delta SLI baselined before the
                # tallies above were restored — re-anchor, or every
                # tpuctl invocation would read the whole persisted
                # interruption history as one fresh burst.
                platform.slo.rebaseline_sources()
        return platform
