from kubeflow_tpu.controlplane.kfam.service import AccessManagement, KfamHttpServer
from kubeflow_tpu.controlplane.kfam.authz import SubjectAccessReviewer

__all__ = ["AccessManagement", "KfamHttpServer", "SubjectAccessReviewer"]
