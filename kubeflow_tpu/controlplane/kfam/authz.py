"""SubjectAccessReview-style authorization.

The reference authorizes every request by minting a SubjectAccessReview for
the trusted USERID_HEADER identity (jupyter-web-app backend,
kubeflow_jupyter/common/auth.py:21-60 ``needs_authorization`` decorator);
kfam handlers do the same via client-go. Here the reviewer evaluates the
same question — can ``user`` ``verb`` resources in ``namespace``? — against
the RoleBindings the profile controller and kfam itself create.
"""

from __future__ import annotations

from typing import List

from kubeflow_tpu.controlplane.runtime.apiserver import InMemoryApiServer

ROLE_VERBS = {
    "kubeflow-admin": {"get", "list", "create", "update", "delete", "admin"},
    "kubeflow-edit": {"get", "list", "create", "update", "delete"},
    "kubeflow-view": {"get", "list"},
}


class SubjectAccessReviewer:
    def __init__(self, api: InMemoryApiServer):
        self.api = api

    def roles_for(self, user: str, namespace: str) -> List[str]:
        roles = []
        for rb in self.api.list("RoleBinding", namespace=namespace,
                                copy=False):
            if any(s.kind == "User" and s.name == user for s in rb.subjects):
                roles.append(rb.role_ref.name)
        return roles

    def can(self, user: str, verb: str, namespace: str) -> bool:
        for role in self.roles_for(user, namespace):
            if verb in ROLE_VERBS.get(role, set()):
                return True
        return False

    def is_cluster_admin(self, user: str) -> bool:
        # Cluster admins are recorded as a label on their Profile.
        for p in self.api.list("Profile", copy=False):
            if (
                p.spec.owner == user
                and p.metadata.labels.get("cluster-admin") == "true"
            ):
                return True
        return False
