"""kfam: profile + contributor access management.

Rebuild of components/access-management (reference routes:
kfam/routers.go:31-101 — POST/DELETE/GET /kfam/v1/bindings,
POST/DELETE /kfam/v1/profiles, GET /kfam/v1/role-clusteradmin,
readiness probe). Contributor grant = paired {RoleBinding,
AuthorizationPolicy principal} (reference bindings.go:76-127 created
RoleBinding + Istio ServiceRoleBinding; we use the modern
AuthorizationPolicy). Identity arrives via the trusted user-id header
injected by the auth proxy (gatekeeper / IAP).

Two layers:
- ``AccessManagement``: the operations, callable in-process (used by the
  dashboard API and tests).
- ``KfamHttpServer``: a stdlib HTTP wrapper exposing the same REST routes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from kubeflow_tpu.controlplane.api.core import (
    RoleBinding,
    RoleRef,
    Subject,
)
from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import Profile, ProfileSpec
from kubeflow_tpu.controlplane.kfam.authz import SubjectAccessReviewer
from kubeflow_tpu.controlplane.runtime.apiserver import (
    AlreadyExistsError,
    InMemoryApiServer,
    NotFoundError,
)
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

log = get_logger("kfam")

ROLE_MAP = {"admin": "kubeflow-admin", "edit": "kubeflow-edit",
            "view": "kubeflow-view"}


class KfamError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


@dataclasses.dataclass
class Binding:
    user: str
    namespace: str
    role: str          # admin | edit | view


class AccessManagement:
    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        user_id_header: str = "x-goog-authenticated-user-email",
        default_chip_quota: int = 0,
    ):
        self.api = api
        self.sar = SubjectAccessReviewer(api)
        self.user_id_header = user_id_header
        self.default_chip_quota = default_chip_quota
        self.requests = registry.counter(
            "kftpu_kfam_requests_total", "kfam ops", ("op", "result")
        )
        self.heartbeat = registry.heartbeat("kfam")

    # ------------- authz helpers -------------

    def _require_ns_admin(self, caller: str, namespace: str) -> None:
        if self.sar.is_cluster_admin(caller):
            return
        if not self.sar.can(caller, "admin", namespace):
            raise KfamError(
                403, f"{caller} is not an admin of namespace {namespace}"
            )

    # ------------- profiles -------------

    def create_profile(self, caller: str, name: str, owner: str = "",
                       tpu_chip_quota: Optional[int] = None) -> Profile:
        self.heartbeat.beat()
        owner = owner or caller
        if owner != caller and not self.sar.is_cluster_admin(caller):
            raise KfamError(403, "only cluster admins create profiles for others")
        # Chip quota is an admin knob: self-service profiles always get the
        # platform default; a caller-chosen quota (including 0 = unlimited)
        # requires cluster admin.
        if tpu_chip_quota is None:
            tpu_chip_quota = self.default_chip_quota
        elif (tpu_chip_quota != self.default_chip_quota
              and not self.sar.is_cluster_admin(caller)):
            raise KfamError(403, "only cluster admins may set tpu_chip_quota")
        try:
            p = self.api.create(Profile(
                metadata=ObjectMeta(name=name),
                spec=ProfileSpec(owner=owner, tpu_chip_quota=tpu_chip_quota),
            ))
            self.requests.inc(op="create-profile", result="ok")
            return p
        except AlreadyExistsError:
            self.requests.inc(op="create-profile", result="conflict")
            raise KfamError(409, f"profile {name} exists")

    def delete_profile(self, caller: str, name: str) -> None:
        self.heartbeat.beat()
        p = self.api.try_get("Profile", name)
        if p is None:
            raise KfamError(404, f"profile {name} not found")
        if p.spec.owner != caller and not self.sar.is_cluster_admin(caller):
            raise KfamError(403, "only the owner or cluster admin may delete")
        self.api.delete("Profile", name)
        self.requests.inc(op="delete-profile", result="ok")

    def profile_exists(self, user: str) -> bool:
        return any(p.spec.owner == user
                   for p in self.api.list("Profile", copy=False))

    # ------------- contributor bindings -------------

    @staticmethod
    def _binding_name(user: str, role: str) -> str:
        # Sanitising '@'/'.' to '-' alone collides ('a.b@c' vs 'a-b@c');
        # a digest of the raw user string keeps names unique per user.
        safe = user.replace("@", "-").replace(".", "-")
        digest = hashlib.sha256(user.encode()).hexdigest()[:8]
        return f"user-{safe}-{digest}-clusterrole-{ROLE_MAP[role]}"

    def _find_binding(self, b: Binding):
        """Locate the RoleBinding for (user, role, namespace) by its
        annotations, so grants created under older naming schemes stay
        manageable after upgrades."""
        for rb in self.api.list("RoleBinding", namespace=b.namespace,
                                copy=False):
            if (rb.metadata.annotations.get("user") == b.user
                    and rb.metadata.annotations.get("role") == b.role):
                return rb
        return None

    def create_binding(self, caller: str, b: Binding) -> None:
        self.heartbeat.beat()
        if b.role not in ROLE_MAP:
            raise KfamError(400, f"unknown role {b.role!r}")
        self._require_ns_admin(caller, b.namespace)
        if self._find_binding(b) is not None:
            raise KfamError(409, "binding exists")
        rb = RoleBinding(
            metadata=ObjectMeta(
                name=self._binding_name(b.user, b.role),
                namespace=b.namespace,
                annotations={"user": b.user, "role": b.role},
            ),
            subjects=[Subject(kind="User", name=b.user)],
            role_ref=RoleRef(name=ROLE_MAP[b.role]),
        )
        try:
            self.api.create(rb)
        except AlreadyExistsError:
            raise KfamError(409, "binding exists")
        # Pair with Istio-level access (reference bindings.go:100-127).
        ap = self.api.try_get(
            "AuthorizationPolicy", "ns-owner-access-istio", b.namespace
        )
        if ap is not None and b.user not in ap.principals:
            ap.principals.append(b.user)
            self.api.update(ap)
        self.requests.inc(op="create-binding", result="ok")

    def delete_binding(self, caller: str, b: Binding) -> None:
        self.heartbeat.beat()
        self._require_ns_admin(caller, b.namespace)
        rb = self._find_binding(b)
        if rb is None:
            raise KfamError(404, "binding not found")
        self.api.delete("RoleBinding", rb.metadata.name, b.namespace)
        ap = self.api.try_get(
            "AuthorizationPolicy", "ns-owner-access-istio", b.namespace
        )
        if ap is not None and b.user in ap.principals:
            owner = ""
            prof = self.api.try_get("Profile", b.namespace)
            if prof is not None:
                owner = prof.spec.owner
            if b.user != owner:
                ap.principals.remove(b.user)
                self.api.update(ap)
        self.requests.inc(op="delete-binding", result="ok")

    def list_bindings(
        self,
        user: Optional[str] = None,
        namespace: Optional[str] = None,
        role: Optional[str] = None,
    ) -> List[Binding]:
        self.heartbeat.beat()
        out = []
        for rb in self.api.list("RoleBinding", namespace=namespace,
                                copy=False):
            u = rb.metadata.annotations.get("user")
            r = rb.metadata.annotations.get("role")
            if not u or not r:
                continue  # infra bindings (default-editor etc.)
            if user is not None and u != user:
                continue
            if role is not None and r != role:
                continue
            out.append(Binding(user=u, namespace=rb.metadata.namespace, role=r))
        # Owners are implicit admins of their profile namespaces.
        for p in self.api.list("Profile", copy=False):
            if user is not None and p.spec.owner != user:
                continue
            if namespace is not None and p.metadata.name != namespace:
                continue
            if role is not None and role != "admin":
                continue
            out.append(Binding(user=p.spec.owner, namespace=p.metadata.name,
                               role="admin"))
        return out


class KfamHttpServer:
    """REST wrapper, same route shapes as the reference router
    (kfam/routers.go:31-101)."""

    def __init__(self, am: AccessManagement, host: str = "127.0.0.1",
                 port: int = 0):
        self.am = am
        am_ref = am

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _caller(self) -> str:
                return self.headers.get(am_ref.user_id_header, "")

            def _send(self, status: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> Dict:
                n = int(self.headers.get("Content-Length", "0") or 0)
                if n == 0:
                    return {}
                return json.loads(self.rfile.read(n))

            def do_GET(self):
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                try:
                    if url.path == "/kfam/v1/bindings":
                        bs = am_ref.list_bindings(
                            user=q.get("user"), namespace=q.get("namespace"),
                            role=q.get("role"),
                        )
                        self._send(200, {"bindings": [dataclasses.asdict(b)
                                                      for b in bs]})
                    elif url.path == "/kfam/v1/role-clusteradmin":
                        self._send(200, am_ref.sar.is_cluster_admin(
                            self._caller()))
                    elif url.path == "/metrics":
                        self._send(200, {"note": "see registry"})
                    elif url.path == "/kfam/v1/health":
                        self._send(200, {"status": "ok"})
                    else:
                        self._send(404, {"error": "not found"})
                except KfamError as e:
                    self._send(e.status, {"error": str(e)})

            def do_POST(self):
                url = urlparse(self.path)
                caller = self._caller()
                if not caller:
                    self._send(401, {"error": "missing identity header"})
                    return
                try:
                    body = self._body()
                    if url.path == "/kfam/v1/profiles":
                        quota = body.get("tpuChipQuota")
                        if quota is not None:
                            try:
                                quota = int(quota)
                            except (ValueError, TypeError) as e:
                                raise KfamError(400, f"bad tpuChipQuota: {e}")
                        p = am_ref.create_profile(
                            caller, body["name"], body.get("owner", ""), quota,
                        )
                        self._send(200, {"name": p.metadata.name})
                    elif url.path == "/kfam/v1/bindings":
                        am_ref.create_binding(caller, Binding(
                            user=body["user"], namespace=body["namespace"],
                            role=body.get("role", "edit"),
                        ))
                        self._send(200, {"status": "created"})
                    else:
                        self._send(404, {"error": "not found"})
                except KfamError as e:
                    self._send(e.status, {"error": str(e)})
                except (KeyError, json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad request: {e}"})

            def do_DELETE(self):
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(urlparse(self.path).query).items()}
                caller = self._caller()
                if not caller:
                    self._send(401, {"error": "missing identity header"})
                    return
                try:
                    if url.path == "/kfam/v1/profiles":
                        am_ref.delete_profile(caller, q["name"])
                        self._send(200, {"status": "deleted"})
                    elif url.path == "/kfam/v1/bindings":
                        am_ref.delete_binding(caller, Binding(
                            user=q["user"], namespace=q["namespace"],
                            role=q.get("role", "edit"),
                        ))
                        self._send(200, {"status": "deleted"})
                    else:
                        self._send(404, {"error": "not found"})
                except KfamError as e:
                    self._send(e.status, {"error": str(e)})
                except KeyError as e:
                    self._send(400, {"error": f"missing param {e}"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
