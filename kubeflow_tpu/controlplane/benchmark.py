"""Control-plane throughput sweep: the apiserver/reconciler benchmark.

Drives a fleet of TpuJobs (gang pods on a FakeKubelet) through creation ->
Running -> Succeeded with the real controller kernel, and reports the
numbers ISSUE 3 puts on the scoreboard:

- **reconciles/sec** and **sweep wall time**: how fast the control plane
  converges a cold fleet (the concurrency wall of arxiv 2011.03641 — the
  coordination layer, not the accelerators, caps scale);
- **kftpu_apiserver_objects_copied_total**: the deterministic read-path
  deepcopy tally, plus a counter-based probe that a namespaced
  ``list("TpuJob", ns)`` copies O(matches) objects — never O(store).
  Counts, not wall-clock, so the CI ``cp-bench-smoke`` gate built on this
  driver cannot flake.
- **latency decomposition** (ISSUE 4): p50/p95/p99 of reconcile
  execution, queue wait and watch-delivery lag from the kernel's
  histograms, so BENCH files track *where time goes*, not just
  throughput.

Everything is in-process and sleep-free (``run_until_idle`` +
``kubelet.tick``), so N=1000 jobs x 4-host gangs runs in seconds.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, Optional

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import MeshAxesSpec, TpuJob, TpuJobSpec
from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.utils.tracing import Tracer


@dataclasses.dataclass
class ControlPlaneReport:
    jobs: int
    pods: int                     # worker pods created (jobs x hosts)
    namespaces: int
    reconciles: int               # reconciles executed across the sweep
    wall_s: float
    reconciles_per_sec: float
    all_succeeded: bool
    phases: Dict[str, int]        # phase -> job count
    store_objects: int            # live objects after the sweep
    copied_during_sweep: Dict[str, int]   # verb -> read-path deepcopies
    # The O(matches) probe: one namespaced copy=True list after the sweep.
    probe_namespace: str
    list_matches: int             # jobs the probe list returned
    list_copies: int              # deepcopies that list performed
    # Latency decomposition (ISSUE 4): p50/p95/p99 over the sweep, from
    # the kernel's histograms. Empty dicts when nothing was observed.
    reconcile_latency_s: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    queue_wait_s: Dict[str, float] = dataclasses.field(default_factory=dict)
    watch_lag_s: Dict[str, float] = dataclasses.field(default_factory=dict)
    # Reconcile spans retained in the tracer's bounded ring — equals
    # `reconciles` while under ring capacity (what obs-smoke gates on);
    # large sweeps keep only the newest spans by design.
    reconcile_spans: int = 0
    # Worker-pool sweep parameters (ISSUE 5): dispatch concurrency and
    # the modeled per-verb API RTT the reconciles paid.
    workers: int = 1
    rtt_s: float = 0.0
    # Converged-state identity: per-kind/phase object counts plus a
    # signature over every (kind, namespace, name, phase) in the store
    # (Events excluded — uuid-named byproducts whose count legitimately
    # varies with reconcile interleaving). Two sweeps that converged to
    # the same world have equal signatures regardless of worker count.
    final_state: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    state_signature: str = ""

    @property
    def copies_scale_with_matches(self) -> bool:
        """True iff the probe list copied exactly its matches — the
        indexed-store contract. An O(store) regression shows up here as
        list_copies ~= store_objects >> list_matches."""
        return self.list_copies == self.list_matches

    def summary(self) -> Dict[str, object]:
        return {
            "jobs": self.jobs,
            "pods": self.pods,
            "reconciles": self.reconciles,
            "sweep_wall_s": round(self.wall_s, 3),
            "reconciles_per_sec": round(self.reconciles_per_sec, 1),
            "kftpu_apiserver_objects_copied_total":
                sum(self.copied_during_sweep.values()),
            "copied_by_verb": dict(self.copied_during_sweep),
            "store_objects": self.store_objects,
            "list_matches": self.list_matches,
            "list_copies": self.list_copies,
            "copies_scale_with_matches": self.copies_scale_with_matches,
            "reconcile_latency_s": dict(self.reconcile_latency_s),
            "queue_wait_s": dict(self.queue_wait_s),
            "watch_lag_s": dict(self.watch_lag_s),
            "reconcile_spans": self.reconcile_spans,
            "workers": self.workers,
            "rtt_s": self.rtt_s,
            "final_state": {k: dict(v) for k, v in self.final_state.items()},
            "state_signature": self.state_signature,
        }


def state_rows(objs) -> list:
    """The fingerprintable rows of a store: one
    ``(kind, namespace, name, phase)`` tuple per stored object, Events
    excluded (uuid-named byproducts whose count varies with reconcile
    interleaving by design). Shard workers ship their rows over the pipe
    and the parent fingerprints the UNION — same rows, same hash, whether
    the world lived in one process or N."""
    rows = []
    for obj in objs:
        if obj.kind == "Event":
            continue
        phase = str(getattr(getattr(obj, "status", None), "phase", "") or "")
        rows.append((obj.kind, obj.metadata.namespace or "",
                     obj.metadata.name, phase))
    return rows


def signature_of_rows(rows) -> tuple:
    """(per-kind phase counts, sha256 signature) over fingerprint rows.
    Order-independent (rows are sorted before hashing), so a union of
    per-shard row lists fingerprints identically to one store's rows."""
    counts: Dict[str, Dict[str, int]] = {}
    for kind, _ns, _name, phase in rows:
        counts.setdefault(kind, {})
        counts[kind][phase or "-"] = counts[kind].get(phase or "-", 0) + 1
    digest = hashlib.sha256(
        "\n".join("|".join(r) for r in sorted(tuple(r) for r in rows)).encode()
    ).hexdigest()
    return counts, digest


def state_fingerprint(objs) -> tuple:
    """(per-kind phase counts, sha256 signature) over the given stored
    objects (``api.list_all()``). The signature covers every
    (kind, namespace, name, phase) — Events excluded — so it is identical
    across worker counts AND across shard layouts iff the sweeps
    converged to the same world. Counts, never wall-clock: the CI gate
    built on this cannot flake."""
    return signature_of_rows(state_rows(objs))


def run_controlplane_sweep(
    *,
    num_jobs: int = 1000,
    num_namespaces: int = 20,
    slice_type: str = "v5e-16",      # 4 hosts -> 4 worker pods per job
    max_rounds: int = 12,
    registry: Optional[MetricsRegistry] = None,
    tracer: Optional[Tracer] = None,
    workers: int = 1,
    rtt_s: float = 0.0,
) -> ControlPlaneReport:
    """``workers`` sizes the manager's reconcile pool (ISSUE 5);
    ``rtt_s`` > 0 models a per-verb API round trip (the latency every
    real control plane pays to its apiserver) via a seeded latency-only
    chaos proxy — the regime where dispatch concurrency, not CPU, is the
    ceiling. Both default off, keeping the historical sweep byte-
    identical."""
    if num_jobs < 1 or num_namespaces < 1:
        raise ValueError("num_jobs and num_namespaces must be >= 1")
    num_namespaces = min(num_namespaces, num_jobs)
    registry = registry or MetricsRegistry()
    # A private tracer per sweep: the ring buffer bounds memory and the
    # CI obs-smoke stage counts reconcile spans out of it.
    tracer = tracer or Tracer()
    api = InMemoryApiServer(registry=registry, tracer=tracer)
    front: object = api
    if rtt_s > 0:
        from kubeflow_tpu.chaos.api import ChaosApiServer, FaultSpec

        # Latency-only rules, no fault bands: every verb the controllers
        # issue sleeps rtt_s before hitting the store (try_get stays
        # free — it models the local informer read). The sleep happens
        # outside the store lock, so concurrent reconciles overlap their
        # RTTs — exactly what the worker pool exists to exploit.
        front = ChaosApiServer(
            api, seed=0, registry=registry,
            rules={"*:*": FaultSpec(latency_s=rtt_s)},
        )
    mgr = ControllerManager(front, registry, tracer=tracer, workers=workers)
    job_ctl = TpuJobController(front, registry, hbm_check=False)
    mgr.register(job_ctl)
    kubelet = FakeKubelet(front, registry,
                          outcome=lambda name: "Succeeded")
    mgr.register(kubelet)

    from kubeflow_tpu.topology import get_slice
    hosts = get_slice(slice_type).num_hosts

    for i in range(num_jobs):
        api.create(TpuJob(
            metadata=ObjectMeta(
                name=f"job-{i:04d}",
                namespace=f"ns-{i % num_namespaces:02d}",
            ),
            spec=TpuJobSpec(
                slice_type=slice_type,
                mesh=MeshAxesSpec(dp=-1),
                backoff_seconds=0.0,
            ),
        ))

    # Reset the tally AFTER fleet creation: the sweep's copy budget is the
    # controllers' read traffic, not the test harness's setup writes.
    api.copied = {}
    reconciles = 0
    t0 = time.perf_counter()
    # Budget: every job reconciles a handful of times (create gang, observe
    # Running, observe Succeeded) and every pod event fans into the kubelet;
    # 40 iterations per job+pod is far above the converged cost and still
    # catches livelocks.
    budget = 40 * num_jobs * (hosts + 1)
    for _ in range(max_rounds):
        reconciles += mgr.run_until_idle(max_iterations=budget,
                                         include_timers_within=30.0)
        kubelet.tick()
        reconciles += mgr.run_until_idle(max_iterations=budget,
                                         include_timers_within=30.0)
        phases = [j.status.phase
                  for j in api.list("TpuJob", copy=False)]
        if all(p in ("Succeeded", "Failed") for p in phases):
            break
    wall = time.perf_counter() - t0
    copied_sweep = dict(api.copied)

    # O(matches) probe: a default (copy=True) namespaced list must deepcopy
    # exactly the objects it returns. Before the secondary indexes, this
    # scanned — and with the old read path deep-copied — the entire store.
    probe_ns = "ns-00"
    before = api.copied.get("list", 0)
    matches = api.list("TpuJob", namespace=probe_ns)
    list_copies = api.copied.get("list", 0) - before

    phase_tally: Dict[str, int] = {}
    for j in api.list("TpuJob", copy=False):
        phase_tally[j.status.phase] = phase_tally.get(j.status.phase, 0) + 1

    store = api.list_all()
    final_state, signature = state_fingerprint(store)
    report = ControlPlaneReport(
        jobs=num_jobs,
        pods=num_jobs * hosts,
        namespaces=num_namespaces,
        reconciles=reconciles,
        wall_s=wall,
        reconciles_per_sec=reconciles / wall if wall > 0 else 0.0,
        all_succeeded=phase_tally.get("Succeeded", 0) == num_jobs,
        phases=phase_tally,
        store_objects=len(store),
        copied_during_sweep=copied_sweep,
        probe_namespace=probe_ns,
        list_matches=len(matches),
        list_copies=list_copies,
        reconcile_latency_s=registry.percentiles(
            "kftpu_reconcile_duration_seconds"),
        queue_wait_s=registry.percentiles("kftpu_workqueue_wait_seconds"),
        watch_lag_s=registry.percentiles(
            "kftpu_watch_delivery_lag_seconds"),
        reconcile_spans=len(tracer.spans("reconcile")),
        workers=workers,
        rtt_s=rtt_s,
        final_state=final_state,
        state_signature=signature,
    )
    mgr.close()     # throwaway manager: release its watch queues
    return report
