"""Control plane: CRD types, controllers, API runtime, access management.

The TPU-native rebuild of the reference's platform kernel (SURVEY.md §1
L1-L2): typed resources in group ``kubeflow.org``-equivalent
(``tpu.kubeflow.org``), an in-memory API server with watch/finalizer/
ownerRef semantics (the envtest analogue the reference gets from
sigs.k8s.io/controller-runtime envtest, reference: components/
profile-controller/controllers/suite_test.go:50-72), a reconciler kernel
(reference: components/common/reconcilehelper/util.go), and the
controllers: TpuJob (gang scheduling on slices), Notebook (+culler),
Profile (multi-tenancy), PodDefault (admission mutation), Tensorboard.
"""
