"""Horizontally sharded control plane (ISSUE 6).

PR 5's reconcile worker pool bought 2.0×, and docs/controlplane-perf.md
is explicit about why it stops there: the GIL serializes the pure-Python
reconcile bodies, so zero-RTT throughput is pinned to one core no matter
how many workers overlap API round trips. This module breaks that ceiling
the way real control planes do — **horizontally**: N shard *processes*,
each running its own ``InMemoryApiServer`` + ``ControllerManager`` (plus
worker pool), with a deterministic router assigning every object to
exactly one shard. This is the coordination-layer limit of
arxiv 2011.03641 ("Exploring the limits of Concurrency in ML Training on
Google TPUs") attacked at the layer the paper names.

Pieces:

- :class:`ShardRouter` — pure function ``route(kind, namespace) → shard``.
  **Contract:** namespaced kinds hash the NAMESPACE alone (the kind does
  not enter the hash), so a TpuJob and every dependent it spawns — gang
  pods, services, events — land on the SAME shard and its controllers
  never need a cross-shard read. Cluster-scoped kinds hash the kind to a
  deterministic HOME shard for fingerprint accounting, and are replicated
  to every shard at create time so the lease holder's singleton
  controllers see them locally wherever the lease lands. The hash is
  blake2s — stable across processes, machines and Python runs (never
  ``hash()``, which is salted per process).
- shard worker processes (:func:`_shard_worker`) — each builds the full
  single-shard stack (apiserver, manager, TpuJob controller, kubelet,
  optional chaos proxy, optional WAL) and serves a small command protocol
  over a pipe. A worker journals every committed write through the WAL
  (fsync'd, in commit order), so SIGKILL at any point replays to the
  exact pre-crash state on restart.
- :class:`ShardedControlPlane` — the parent-side handle: routes object
  creation, drives reconcile rounds on ALL shards concurrently (each
  round executes in N processes in parallel — this is where the
  horizontal speedup comes from), unions per-shard fingerprints, and
  owns **leader election**: exactly one live shard holds the lease and
  runs the singleton controllers (the admission-ledger / defrag-style
  loops that must not run twice). The election is epoch-numbered; a
  killed leader's lease moves to the lowest-numbered survivor, and a
  restarted ex-leader comes back as a follower.
- :func:`run_sharded_sweep` — the bench driver behind
  ``bench.py controlplane --shards N``: the same fleet the serial sweep
  drives, routed across shards, hard-gated (by the caller) on
  cross-shard union ``state_fingerprint()`` equality with the serial
  run.

Failure/recovery contract (proved by the sharded chaos soak): a shard
killed mid-soak replays its WAL to the exact pre-crash store, its manager
resubscribes (``CachedReader`` seeding + watch bookmarks), and the fleet
converges with a byte-identical union fingerprint — recovery IS the
normal resync path, not a special case.
"""

from __future__ import annotations

import dataclasses
import hashlib
import multiprocessing
import os
import signal
import threading
import time
import traceback
from typing import Any, Dict, Iterable, List, Optional, Tuple

from kubeflow_tpu.controlplane.benchmark import (
    signature_of_rows,
    state_rows,
)
from kubeflow_tpu.controlplane.runtime.apiserver import CLUSTER_SCOPED
from kubeflow_tpu.utils import get_logger

log = get_logger("shard")

SHARD_DIR_FMT = "shard-{:02d}"


# --------------------------------------------------------------------------
# Router
# --------------------------------------------------------------------------


class ShardRouter:
    """Deterministic ``(kind, namespace) → shard`` routing.

    Namespaced kinds route by namespace ONLY — colocation is the whole
    contract: every object a controller reads or writes while reconciling
    a key lives in that key's namespace, so per-namespace placement makes
    each shard's store closed under reconciliation. Cluster-scoped kinds
    (no namespace to hash) route by kind, giving each cluster-scoped
    family a single deterministic home shard — the shard that REPORTS
    them in fingerprint rows; the objects themselves are replicated to
    every shard by :meth:`ShardedControlPlane.create` so singleton
    controllers can read them on whichever shard holds the lease.
    """

    def __init__(self, num_shards: int):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.num_shards = int(num_shards)

    @staticmethod
    def _bucket(token: str) -> int:
        h = hashlib.blake2s(token.encode(), digest_size=8).digest()
        return int.from_bytes(h, "big")

    def route(self, kind: str, namespace: str = "") -> int:
        if self.num_shards == 1:
            return 0
        if kind in CLUSTER_SCOPED or not namespace:
            return self._bucket(f"kind:{kind}") % self.num_shards
        return self._bucket(f"ns:{namespace}") % self.num_shards

    def route_doc(self, doc: Dict[str, Any]) -> int:
        meta = doc.get("metadata") or {}
        return self.route(doc.get("kind", ""), meta.get("namespace", ""))


# --------------------------------------------------------------------------
# Shard worker (child process)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ShardSpec:
    """Everything a shard process needs to build itself — plain picklable
    data, identical across restarts of the same shard (the restart path
    relies on the WAL living at the same spec-derived location)."""

    shard_id: int
    num_shards: int
    workers: int = 1
    rtt_us: int = 0                  # modeled per-verb API RTT
    state_dir: str = ""              # "" = no WAL (pure-perf bench mode)
    seed: int = 0
    conflict_rate: float = 0.0
    transient_rate: float = 0.0
    work_ticks: int = 0              # 0 = pods succeed on first tick
    capacity: Optional[Dict[str, int]] = None
    wal_fsync: bool = True
    bookmark_interval: int = 50
    # Cross-shard admission ledger (ISSUE 8): CLUSTER slice capacity,
    # served by the lease-holding shard. Mutually exclusive with the
    # per-shard ``capacity`` map — that one is exactly the double-admit
    # hazard the ledger exists to close. ``ledger_conn`` is this shard's
    # client pipe to the parent relay; ``ledger_serve_conn`` is the pipe
    # the shard answers on WHEN it holds the lease.
    global_capacity: Optional[Dict[str, int]] = None
    ledger_conn: Any = None
    ledger_serve_conn: Any = None
    # ISSUE 16: build the shard's hot locks through traced wrappers and
    # install the workqueue oracle; the parent collects each shard's
    # lock-order graph + oracle verdict via the "locktrace" command.
    locktrace: bool = False
    # ISSUE 17: per-shard remediation controller next to the SLO engine.
    # Off by default so existing sharded soaks keep their seed contracts
    # — a paging objective with remediation on fires requeue actions
    # that change timer scheduling.
    remediate: bool = False


class ShardSingleton:
    """The singleton-controller stand-in registered on the LEADER shard
    only: represents the loops that must run exactly once platform-wide
    (a cross-shard admission ledger, defrag-style background sweeps).
    Running two of these would double-admit / double-migrate — which is
    precisely why the sharded plane needs leader election at all."""

    NAME = "shard-singleton"


def _wal_dir(spec: ShardSpec) -> str:
    return os.path.join(spec.state_dir, SHARD_DIR_FMT.format(spec.shard_id))


def _shard_worker(conn, spec: ShardSpec) -> None:
    """Child-process body: build one complete control-plane shard and
    serve commands until "stop" (or the parent goes away)."""
    # Imports INSIDE the worker keep the module import cheap for the
    # parent and make spawn-started children pay only for what they use.
    from kubeflow_tpu.chaos.api import ChaosApiServer, FaultSpec
    from kubeflow_tpu.chaos.preemptor import SlicePreemptor
    from kubeflow_tpu.controlplane.api import object_from_dict
    from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
    from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
    from kubeflow_tpu.controlplane.runtime import (
        ControllerManager,
        ExponentialBackoffLimiter,
        InMemoryApiServer,
    )
    from kubeflow_tpu.controlplane.runtime.reconciler import Controller
    from kubeflow_tpu.controlplane.wal import WriteAheadLog, wal_path
    from kubeflow_tpu.utils import locktrace
    from kubeflow_tpu.utils.monitoring import MetricsRegistry
    from kubeflow_tpu.utils.tracing import Tracer

    if spec.locktrace:
        # Before ANY traced lock exists in this process — the apiserver
        # store lock and the manager queue lock are built through the
        # locktrace factories, which consult the flag at construction.
        locktrace.enable()
    registry = MetricsRegistry()
    tracer = Tracer()
    api = InMemoryApiServer(registry=registry, tracer=tracer,
                            bookmark_interval=spec.bookmark_interval)

    wal = None
    wal_replayed = 0
    if spec.state_dir:
        sdir = _wal_dir(spec)
        os.makedirs(sdir, exist_ok=True)
        wal = WriteAheadLog(wal_path(sdir), fsync=spec.wal_fsync)
        # Restart path: replay the fsync'd record stream BEFORE attaching
        # the journal (replay must not re-journal) and before any
        # controller subscribes (their watch replay then sees the
        # recovered store).
        wal_replayed = wal.replay(api)
        wal.attach(api)

    front: Any = api
    chaos = None
    rtt_s = spec.rtt_us * 1e-6
    if spec.conflict_rate > 0 or spec.transient_rate > 0:
        rules = {
            "update:*": FaultSpec(conflict_rate=spec.conflict_rate,
                                  transient_rate=spec.transient_rate,
                                  latency_s=rtt_s),
            "update_status:*": FaultSpec(conflict_rate=spec.conflict_rate,
                                         transient_rate=spec.transient_rate,
                                         latency_s=rtt_s),
            "create:*": FaultSpec(transient_rate=spec.transient_rate,
                                  latency_s=rtt_s),
            "delete:*": FaultSpec(transient_rate=spec.transient_rate,
                                  latency_s=rtt_s),
            "list:*": FaultSpec(transient_rate=spec.transient_rate,
                                latency_s=rtt_s),
        }
        chaos = ChaosApiServer(api, seed=spec.seed + spec.shard_id,
                               registry=registry, rules=rules)
        front = chaos
    elif rtt_s > 0:
        # Latency-only proxy: the modeled apiserver round trip every real
        # control plane pays (same shape as the serial bench's rtt_s).
        chaos = ChaosApiServer(api, seed=spec.seed + spec.shard_id,
                               registry=registry,
                               rules={"*:*": FaultSpec(latency_s=rtt_s)})
        front = chaos

    mgr = ControllerManager(
        front, registry, tracer=tracer, workers=spec.workers,
        limiter=ExponentialBackoffLimiter(seed=spec.seed + 101
                                          + spec.shard_id),
    )
    if spec.locktrace:
        mgr.oracle = locktrace.WorkqueueOracle()
    capacity = dict(spec.capacity) if spec.capacity else None
    ledger_client = None
    if spec.global_capacity is not None:
        from kubeflow_tpu.controlplane.ledger import LedgerClient

        # Slice capacity is CLUSTER state: reservations route (via the
        # parent relay) to the lease holder's LedgerService, never a
        # per-shard map — a local map on two shards is exactly the
        # double-admit the PR-6 follow-up left open.
        ledger_client = LedgerClient(spec.ledger_conn)
    job_ctl = TpuJobController(front, registry, capacity=capacity,
                               hbm_check=False, ledger=ledger_client)
    mgr.register(job_ctl)

    seen: Dict[str, int] = {}
    if spec.work_ticks > 0:
        def outcome(name: str) -> Optional[str]:
            seen[name] = seen.get(name, 0) + 1
            return "Succeeded" if seen[name] >= spec.work_ticks else None
    else:
        def outcome(name: str) -> Optional[str]:
            return "Succeeded"

    kubelet = FakeKubelet(front, registry, outcome=outcome)
    mgr.register(kubelet)
    # Slice preemption models hardware and targets the RAW store.
    preemptor = SlicePreemptor(api, seed=spec.seed + 202 + spec.shard_id,
                               capacity=capacity, registry=registry)

    # Per-shard goodput ledger (ISSUE 10): tick-driven (one tick per
    # parent "round"), journaled under the shard dir with the same
    # fsync discipline as the WAL, unit ids shard-prefixed so rows
    # union like state_fingerprint() rows. A SIGKILLed shard rebuilds
    # its ledger by replaying the journal through the same application
    # path — byte-identical accounting, gated by shard-smoke.
    goodput_acc = None
    goodput_tick = 0
    if spec.capacity:
        from kubeflow_tpu.obs.goodput import (
            GOODPUT_JOURNAL,
            GoodputAccountant,
        )

        gp_journal = (os.path.join(_wal_dir(spec), GOODPUT_JOURNAL)
                      if spec.state_dir else "")
        goodput_acc = GoodputAccountant.from_capacity(
            spec.capacity,
            unit_prefix=f"sh{spec.shard_id:02d}:",
            registry=registry, track_rollback=False,
            journal_path=gp_journal, fsync=spec.wal_fsync)
        if gp_journal and os.path.exists(gp_journal):
            goodput_acc.replay_from(gp_journal)
            goodput_tick = goodput_acc.last_tick()
        # Attach AFTER WAL replay: the initial watch sync baselines the
        # job table at the recovered store (replayed restart counters
        # must not read as fresh interruptions).
        goodput_acc.attach(api)

    # Per-shard SLO engine + flight recorder (ISSUE 15): tick-driven
    # like the goodput ledger, alert journal under the shard dir with
    # the same fsync discipline — a SIGKILLed shard's engine replays
    # alerts.jsonl byte-identically (the slo-smoke/shard gate). A
    # respawn (wal_replayed > 0) dumps the flight ring immediately:
    # the fresh incarnation records what it knows about the crash it
    # replaced, stitched cross-shard by `tpuctl flight show`.
    slo_engine = None
    recorder = None
    if spec.capacity:
        from kubeflow_tpu.obs.flight import FlightRecorder
        from kubeflow_tpu.obs.slo import (
            ALERTS_JOURNAL,
            SLOEngine,
            soak_objectives,
        )

        sdir = _wal_dir(spec) if spec.state_dir else ""
        # The recorder's clock is the shard's goodput tick, so every
        # ring entry (events, metric deltas, alerts) shares one clock
        # domain and cross-shard stitches stay causally ordered.
        recorder = FlightRecorder(shard=f"sh{spec.shard_id:02d}",
                                  tracer=tracer, registry=registry,
                                  now_fn=lambda: goodput_tick)
        recorder.attach(api)
        objectives = soak_objectives(goodput_acc)
        if spec.remediate:
            # ISSUE 17: watch the remediation controller's own disable
            # gauge, so an auto-disabled playbook pages like any SLO.
            from kubeflow_tpu.obs.remediate import remediation_objective

            objectives = objectives + [remediation_objective()]
        slo_engine = SLOEngine(
            registry,
            objectives=objectives,
            journal_path=(os.path.join(sdir, ALERTS_JOURNAL)
                          if sdir else ""),
            fsync=spec.wal_fsync,
            recorder=recorder,
            dump_dir=sdir,
        )
        if sdir and os.path.exists(os.path.join(sdir, ALERTS_JOURNAL)):
            slo_engine.replay_from(os.path.join(sdir, ALERTS_JOURNAL))
        if goodput_acc is not None:
            slo_engine.add_guard(
                "goodput-conservation",
                lambda: goodput_acc.conservation()["exact"])
        if sdir and wal_replayed > 0:
            recorder.record("respawn", {"shard": spec.shard_id,
                                        "wal_replayed": wal_replayed})
            recorder.dump(sdir, reason="shard-respawn")

    # Per-shard remediation controller (ISSUE 17): subscribes to the
    # shard's own SLO engine and acts through the shard's own seams
    # (its manager's park-path timers). The action journal lives under
    # the shard dir with WAL fsync discipline — a SIGKILLed shard
    # replays actions.jsonl byte-identically (pending verdicts re-arm
    # at their original due ticks), gated by remediate-smoke.
    remediation = None
    if spec.capacity and spec.remediate and slo_engine is not None:
        from kubeflow_tpu.obs.remediate import (
            ACTIONS_JOURNAL,
            RemediationController,
            requeue_playbook,
        )

        act_journal = (os.path.join(sdir, ACTIONS_JOURNAL) if sdir else "")
        remediation = RemediationController(
            registry,
            engine=slo_engine,
            playbooks=[
                # Same cadence as the serial soak wiring: the verify
                # window must cover fault + clear_after quiet evals, or
                # a working playbook reads as unpaid and auto-disables.
                requeue_playbook(mgr, budget=3, cooldown=4.0,
                                 verify_after=4.0),
            ],
            journal_path=act_journal,
            fsync=spec.wal_fsync,
            recorder=recorder,
            dump_dir=sdir,
            accountant=goodput_acc,
        )
        if act_journal and os.path.exists(act_journal):
            remediation.replay_from(act_journal)

    class _Singleton(Controller):
        NAME = ShardSingleton.NAME
        WATCH_KINDS = ("PlatformConfig",)

        def reconcile(self, namespace, name):
            return None

    singleton: Optional[Controller] = None
    leading = False
    ledger_service = None

    def _set_leading(want: bool) -> None:
        nonlocal ledger_service
        if spec.global_capacity is None:
            return
        from kubeflow_tpu.controlplane.ledger import (
            LedgerService,
            ledger_journal_path,
        )

        if want and ledger_service is None:
            # The lease holder serves the cluster ledger. The journal
            # lives at the state-dir ROOT (not per-shard): the lease
            # moves, and the next leader must replay the SAME
            # reservation history or the failover reopens the
            # double-admit window.
            ledger_service = LedgerService(
                spec.global_capacity,
                spec.ledger_serve_conn,
                journal_path=(ledger_journal_path(spec.state_dir)
                              if spec.state_dir else ""),
                fsync=spec.wal_fsync,
                # The shard's tracer: ledger.<op> spans adopt the
                # requesting shard's trace id, land in THIS shard's
                # trace.jsonl, and shard-aware `tpuctl trace` stitches
                # the cross-shard round-trip into one timeline.
                tracer=tracer,
            ).start()
        elif not want and ledger_service is not None:
            ledger_service.stop()
            ledger_service = None

    def handle(msg: Tuple) -> Any:
        nonlocal singleton, leading, goodput_tick
        cmd = msg[0]
        if cmd == "create":
            n = 0
            for doc in msg[1]:
                api.create(object_from_dict(doc))
                n += 1
            return n
        if cmd == "round":
            window = float(msg[1])
            # Optional third field: fire parked requeue timers due within
            # that many seconds ONCE before draining — the retry
            # primitive for capacity/ledger-parked gangs (a drain window
            # wider than the 5s park interval would spin instead).
            if len(msg) > 2 and msg[2]:
                mgr.kick_timers(float(msg[2]))
            n = mgr.run_until_idle(max_iterations=500000,
                                   include_timers_within=window)
            kubelet.tick()
            n += mgr.run_until_idle(max_iterations=500000,
                                    include_timers_within=window)
            if goodput_acc is not None:
                # Reclaimed slices stop being offered capacity; then
                # attribute this round's slice-ticks.
                goodput_acc.set_capacity(dict(capacity or {}))
                goodput_acc.pump()
                goodput_tick += 1
                goodput_acc.tick(goodput_tick)
            if slo_engine is not None:
                recorder.pump()
                recorder.record_metric_deltas()
                fired = slo_engine.evaluate(goodput_tick)
                if remediation is not None and remediation.tick(
                        goodput_tick, fired=fired):
                    # An action ran (requeue fills the workqueue):
                    # drain again so this round's terminal/phase report
                    # reflects the remediated state, not the backlog
                    # the remediation just created.
                    n += mgr.run_until_idle(max_iterations=500000,
                                            include_timers_within=window)
            if spec.state_dir:
                # Spans (reconciles, ledger round-trips) land in the
                # shard's trace file so shard-aware `tpuctl trace` can
                # stitch cross-shard timelines; rotated past the byte
                # cap like the Platform file (trace readers merge both
                # generations).
                from kubeflow_tpu.utils.tracing import Tracer

                trace_path = os.path.join(_wal_dir(spec), "trace.jsonl")
                tracer.export_new_jsonl(trace_path)
                Tracer.rotate_jsonl(trace_path)
            phases: Dict[str, int] = {}
            terminal = True
            for j in api.list("TpuJob", copy=False):
                p = j.status.phase or "-"
                phases[p] = phases.get(p, 0) + 1
                if p not in ("Succeeded", "Failed"):
                    terminal = False
            return {"reconciles": n, "terminal": terminal,
                    "phases": phases}
        if cmd == "fingerprint":
            # Cluster-scoped kinds are REPLICATED to every shard (so the
            # lease holder's singleton controllers see them locally, on
            # whichever shard the lease lands) — only their HOME shard
            # reports them, so the cross-shard union counts each exactly
            # once and stays byte-comparable to a serial world.
            router = ShardRouter(spec.num_shards)
            rows = state_rows(api.list_all())
            return [r for r in rows
                    if r[0] not in CLUSTER_SCOPED
                    or router.route(r[0]) == spec.shard_id]
        if cmd == "quiesce":
            if chaos is not None:
                chaos.quiesce()
            preemptor.restore_capacity()
            return None
        if cmd == "preempt":
            return preemptor.preempt_random()
        if cmd == "lead":
            want = bool(msg[1])
            if want and singleton is None:
                singleton = _Singleton(front, registry)
                mgr.register(singleton)
            elif not want and singleton is not None:
                mgr.unregister(singleton)
                singleton = None
            _set_leading(want)
            leading = want
            return leading
        if cmd == "ledger":
            return (ledger_service.ledger.snapshot()
                    if ledger_service is not None else None)
        if cmd == "ledger_prune":
            # Anti-entropy GC, leader only: drop reservations whose gang
            # exists on NO shard (deleted while its owning controller
            # was down — nobody left to release by uid).
            if ledger_service is None:
                return None
            return ledger_service.handle("prune", (msg[1],))
        if cmd == "job_uids":
            return [j.metadata.uid
                    for j in api.list("TpuJob", copy=False)
                    if j.status.phase not in ("Succeeded", "Failed")]
        if cmd == "goodput":
            if goodput_acc is None:
                return None
            cats, digest = goodput_acc.fingerprint()
            return {
                "rows": goodput_acc.rows(),
                "fingerprint": digest,
                "categories_ticks": cats,
                "conserved": goodput_acc.conservation()["exact"],
                "summary": goodput_acc.snapshot(),
                "tick": goodput_tick,
            }
        if cmd == "slo":
            if slo_engine is None:
                return None
            return {
                "fingerprint": slo_engine.fingerprint(),
                "states": slo_engine.states(),
                "pages": slo_engine.pages_by_objective(),
                "transitions": slo_engine.transitions_total(),
                "flight_dumps": list(recorder.dumps),
            }
        if cmd == "remediate":
            if remediation is None:
                return None
            if len(msg) > 1 and msg[1] == "settle":
                # Drain outstanding verdicts (advancing a settle-local
                # clock, never touching goodput_tick) so every journaled
                # action carries a journaled verdict before the parent
                # reads the scoreboard — the soak's end-of-run contract.
                t = float(goodput_tick)
                for _ in range(100):
                    if not remediation.snapshot()["pending"]:
                        break
                    t += 1.0
                    remediation.tick(t, act=False)
            return {
                "fingerprint": remediation.fingerprint(),
                "snapshot": remediation.snapshot(),
            }
        if cmd == "locktrace":
            if not spec.locktrace:
                return None
            rep = locktrace.report()
            rep["oracle"] = mgr.oracle.summary()
            # Diagnostic only — the parent cannot see child threads, so
            # the shard names its own. The worker pool is alive between
            # rounds by design; leak checks happen after close().
            rep["threads"] = sorted(
                t.name for t in threading.enumerate() if t.is_alive())
            return rep
        if cmd == "info":
            return {
                "shard_id": spec.shard_id,
                "leading": leading,
                "controllers": [c.NAME for c in mgr.controllers],
                "workers": spec.workers,
                "wal_appended": wal.appended if wal else 0,
                "wal_replayed": wal_replayed,
                "store_objects": len(api.list_all()),
                "injected": dict(chaos.injected) if chaos else {},
                "replayed": dict(api.replayed),
            }
        raise ValueError(f"unknown shard command {cmd!r}")

    try:
        while True:
            try:
                msg = conn.recv()
            except EOFError:
                break           # parent went away: shut down quietly
            if msg[0] == "stop":
                conn.send(("ok", None))
                break
            try:
                conn.send(("ok", handle(msg)))
            except BaseException:
                conn.send(("err", traceback.format_exc()))
    finally:
        mgr.close()
        if ledger_service is not None:
            ledger_service.stop()
        if remediation is not None:
            remediation.close()
        if slo_engine is not None:
            slo_engine.close()
        if recorder is not None:
            recorder.detach()
        if wal is not None:
            wal.close()


# --------------------------------------------------------------------------
# Parent-side handle
# --------------------------------------------------------------------------


class ShardError(RuntimeError):
    pass


class ShardedControlPlane:
    """Parent-side handle over N shard processes.

    Reconcile rounds are dispatched to every live shard before any reply
    is awaited, so the shards' rounds execute concurrently — N stores, N
    GILs, N worker pools. Leader election: the lease sits with the
    lowest-numbered LIVE shard; every membership change (kill, restart)
    re-runs the election, bumps the epoch, and pushes the lead/follow
    verdict to every survivor (the restarted ex-leader explicitly comes
    back as a follower).
    """

    def __init__(
        self,
        num_shards: int,
        *,
        workers: int = 1,
        rtt_us: int = 0,
        state_dir: str = "",
        seed: int = 0,
        conflict_rate: float = 0.0,
        transient_rate: float = 0.0,
        work_ticks: int = 0,
        capacity_by_shard: Optional[Dict[int, Dict[str, int]]] = None,
        global_capacity: Optional[Dict[str, int]] = None,
        wal_fsync: bool = True,
        start_method: str = "fork",
        locktrace: bool = False,
        remediate: bool = False,
    ):
        self.router = ShardRouter(num_shards)
        self.num_shards = int(num_shards)
        self._base = dict(
            workers=workers, rtt_us=rtt_us, state_dir=state_dir, seed=seed,
            conflict_rate=conflict_rate, transient_rate=transient_rate,
            work_ticks=work_ticks, wal_fsync=wal_fsync,
            locktrace=locktrace, remediate=remediate,
        )
        self._capacity_by_shard = dict(capacity_by_shard or {})
        if start_method not in multiprocessing.get_all_start_methods():
            start_method = "spawn"
        self._ctx = multiprocessing.get_context(start_method)
        # Cross-shard admission ledger plumbing (ISSUE 8): per-shard
        # client and serve PIPES plus a parent-side relay thread that
        # forwards every request to the current lease holder. Pipes, not
        # a shared queue: a queue's reader lock is held while blocked in
        # get, so SIGKILLing the leader mid-poll would leave the lock
        # owned by a corpse and deadlock every future leader; pipe ends
        # are single-process and a dead peer degrades to a timeout —
        # the fail-closed path. Each (re)spawn mints FRESH pipes (see
        # _spawn): a shard killed mid-send leaves a torn pickle frame no
        # recv() can resynchronize, so the respawn must not re-inherit
        # the old stream.
        self._global_capacity = (dict(global_capacity)
                                 if global_capacity is not None else None)
        self._ledger_child_conns: Dict[int, Any] = {}
        self._ledger_serve_child: Dict[int, Any] = {}
        self._ledger_relay = None
        if self._global_capacity is not None:
            from kubeflow_tpu.controlplane.ledger import LedgerRelay

            self._ledger_relay = LedgerRelay(
                {}, {}, leader_of=lambda: self.leader_id,
            ).start()
        self._procs: Dict[int, Any] = {}
        self._conns: Dict[int, Any] = {}
        self._dead: set = set()
        self.leader_id: Optional[int] = None
        self.epoch = 0
        for i in range(self.num_shards):
            self._spawn(i)
        self._elect()

    # ----------------- lifecycle -----------------

    def _spec(self, shard_id: int) -> ShardSpec:
        return ShardSpec(shard_id=shard_id, num_shards=self.num_shards,
                         capacity=self._capacity_by_shard.get(shard_id),
                         global_capacity=self._global_capacity,
                         ledger_conn=self._ledger_child_conns.get(shard_id),
                         ledger_serve_conn=(
                             self._ledger_serve_child.get(shard_id)),
                         **self._base)

    def _spawn(self, shard_id: int) -> None:
        if self._ledger_relay is not None:
            # Fresh ledger pipes for every (re)spawn: the relay swaps
            # them in and closes the previous pair, so a stream torn by
            # a mid-send SIGKILL dies with the process that tore it.
            client_parent, client_child = self._ctx.Pipe()
            serve_parent, serve_child = self._ctx.Pipe()
            self._ledger_child_conns[shard_id] = client_child
            self._ledger_serve_child[shard_id] = serve_child
            self._ledger_relay.replace(shard_id, client_parent,
                                       serve_parent)
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_shard_worker, args=(child_conn, self._spec(shard_id)),
            daemon=True, name=f"kftpu-shard-{shard_id}",
        )
        proc.start()
        child_conn.close()
        if self._ledger_relay is not None:
            # The child inherited its ledger ends at fork; drop the
            # parent's copies (the relay holds the parent-side ends).
            self._ledger_child_conns.pop(shard_id).close()
            self._ledger_serve_child.pop(shard_id).close()
        self._procs[shard_id] = proc
        self._conns[shard_id] = parent_conn
        self._dead.discard(shard_id)

    def alive(self) -> List[int]:
        return [i for i in sorted(self._procs)
                if i not in self._dead and self._procs[i].is_alive()]

    def kill(self, shard_id: int) -> None:
        """SIGKILL the shard process — the process-level fault the chaos
        layer injects. No flush, no goodbye: exactly what the WAL's
        fsync-per-record discipline exists to survive."""
        proc = self._procs[shard_id]
        if proc.is_alive():
            os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10)
        try:
            self._conns[shard_id].close()
        except OSError:
            pass
        self._dead.add(shard_id)
        self._elect()
        log.warning("shard killed", kv={"shard": shard_id,
                                        "leader": self.leader_id})

    def restart(self, shard_id: int) -> None:
        """Respawn a killed shard. The fresh process replays the shard's
        WAL before serving — rejoining with its exact pre-crash state —
        and the election runs again (a restarted ex-leader follows)."""
        if shard_id not in self._dead:
            raise ShardError(f"shard {shard_id} is not dead")
        self._spawn(shard_id)
        self._elect()

    def close(self) -> None:
        if self._ledger_relay is not None:
            self._ledger_relay.stop()
        for i in self.alive():
            try:
                self._call(i, "stop")
            except (ShardError, OSError, EOFError):
                pass
        for i, proc in self._procs.items():
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)
        for conn in self._conns.values():
            try:
                conn.close()
            except OSError:
                pass

    # ----------------- command plumbing -----------------

    def _call(self, shard_id: int, *msg) -> Any:
        conn = self._conns[shard_id]
        conn.send(msg)
        status, payload = conn.recv()
        if status == "err":
            raise ShardError(f"shard {shard_id}: {payload}")
        return payload

    def _broadcast(self, *msg) -> Dict[int, Any]:
        """Send to every live shard FIRST, then collect replies: the
        shards execute the command concurrently — this is the horizontal
        parallelism (each round runs in N processes at once). EVERY reply
        is drained before an error is raised — bailing on the first
        ``err`` would leave later shards' replies in their pipes, and the
        next command on those connections would read a stale payload as
        its answer."""
        ids = self.alive()
        for i in ids:
            self._conns[i].send(msg)
        out: Dict[int, Any] = {}
        errors: List[str] = []
        for i in ids:
            status, payload = self._conns[i].recv()
            if status == "err":
                errors.append(f"shard {i}: {payload}")
            else:
                out[i] = payload
        if errors:
            raise ShardError("; ".join(errors))
        return out

    # ----------------- leader election -----------------

    def _elect(self) -> None:
        alive = self.alive()
        if self.leader_id is not None and self.leader_id in alive:
            # Lease renewal: the incumbent holds the lease while alive. A
            # restarted ex-leader must NOT steal it back — leadership only
            # moves when the holder dies (otherwise every crash-replay
            # restart would flap the singleton controllers twice).
            new_leader: Optional[int] = self.leader_id
        else:
            new_leader = min(alive) if alive else None
        if new_leader != self.leader_id:
            self.epoch += 1
            log.info("leader elected", kv={
                "leader": new_leader, "epoch": self.epoch,
            })
        self.leader_id = new_leader
        for i in alive:
            self._call(i, "lead", i == new_leader)

    # ----------------- operations -----------------

    def create(self, docs: Iterable[Dict[str, Any]]) -> Dict[int, int]:
        """Route manifests to their shards and create them; returns
        objects created per shard. Cluster-scoped kinds are REPLICATED to
        every shard: the lease can land on any shard, and the singleton
        controllers running there must see cluster-scoped state in their
        local store (the ``fingerprint`` command counts each replica set
        once, at its home shard). Singleton WRITES to cluster-scoped
        objects still need the cross-shard service the ROADMAP defers —
        a local write would only update one replica."""
        by_shard: Dict[int, List[Dict[str, Any]]] = {}
        for doc in docs:
            if doc.get("kind", "") in CLUSTER_SCOPED:
                for shard_id in range(self.num_shards):
                    by_shard.setdefault(shard_id, []).append(doc)
            else:
                by_shard.setdefault(self.router.route_doc(doc),
                                    []).append(doc)
        out = {}
        for shard_id, batch in sorted(by_shard.items()):
            if shard_id in self._dead:
                raise ShardError(
                    f"cannot create on dead shard {shard_id}")
            out[shard_id] = self._call(shard_id, "create", batch)
        return out

    def round(self, window: float = 30.0,
              kick: float = 0.0) -> Dict[int, Dict[str, Any]]:
        """One reconcile round on every live shard, concurrently.
        ``kick`` > 0 first fires parked requeue timers due within that
        many seconds exactly once (see ``ControllerManager.kick_timers``)
        so capacity-parked gangs retry each round without the drain
        window having to exceed — and then spin on — their park
        interval."""
        return self._broadcast("round", window, kick)

    def quiesce(self) -> None:
        self._broadcast("quiesce")

    def preempt(self, shard_id: int) -> Optional[str]:
        return self._call(shard_id, "preempt")

    def info(self) -> Dict[int, Dict[str, Any]]:
        return {i: self._call(i, "info") for i in self.alive()}

    def locktrace_reports(self) -> Dict[int, Dict[str, Any]]:
        """Every live shard's lock-order graph + workqueue-oracle
        verdict (``utils.locktrace.report()`` shape, plus ``oracle``).
        Empty payloads when the plane runs without ``locktrace=True``."""
        return {i: rep
                for i, rep in self._broadcast("locktrace").items()
                if rep is not None}

    def ledger_snapshot(self) -> Optional[Dict[str, Any]]:
        """The leader's admission-ledger state (None when no global
        capacity is configured or no leader is alive)."""
        if self.leader_id is None:
            return None
        return self._call(self.leader_id, "ledger")

    def ledger_gc(self) -> Optional[list]:
        """Anti-entropy for the admission ledger: collect every live
        (non-terminal) TpuJob uid across ALL shards and have the leader
        drop reservations held by gangs that exist nowhere — the leak
        path is a gang deleted while its owning controller was down.
        Returns the pruned uids (None without a configured ledger).
        Call from a quiesced plane: a uid snapshot racing an in-flight
        admission could prune a reservation whose gang is mid-create."""
        if self.leader_id is None or self._global_capacity is None:
            return None
        live: List[str] = []
        for uids in self._broadcast("job_uids").values():
            live.extend(uids)
        return self._call(self.leader_id, "ledger_prune", live)

    def shard_goodput(self, shard_id: int) -> Optional[Dict[str, Any]]:
        """One shard's goodput ledger payload (rows + fingerprint +
        conservation verdict); None when the shard tracks no capacity."""
        return self._call(shard_id, "goodput")

    def shard_goodput_fingerprint(self, shard_id: int) -> Optional[str]:
        payload = self.shard_goodput(shard_id)
        return payload["fingerprint"] if payload else None

    def shard_slo(self, shard_id: int) -> Optional[Dict[str, Any]]:
        """One shard's SLO engine payload (alert fingerprint, states,
        page counts, flight dumps); None when the shard runs none."""
        return self._call(shard_id, "slo")

    def shard_slo_fingerprint(self, shard_id: int) -> Optional[str]:
        payload = self.shard_slo(shard_id)
        return payload["fingerprint"] if payload else None

    def shard_remediation(self, shard_id: int,
                          settle: bool = False) -> Optional[Dict[str, Any]]:
        """One shard's remediation payload (action-journal fingerprint +
        scoreboard snapshot); None when the shard runs no controller.
        ``settle=True`` first drains outstanding verdicts so every
        journaled action carries a journaled verdict."""
        if settle:
            return self._call(shard_id, "remediate", "settle")
        return self._call(shard_id, "remediate")

    def shard_remediation_fingerprint(self, shard_id: int) -> Optional[str]:
        payload = self.shard_remediation(shard_id)
        return payload["fingerprint"] if payload else None

    def remediation_union(self, settle: bool = False) -> Dict[str, Any]:
        """Every live shard's remediation scoreboard folded into one
        view: actions/verdicts summed per playbook, disabled playbooks
        unioned, pending counted fleet-wide."""
        playbooks: Dict[str, Dict[str, Any]] = {}
        actions = 0
        pending = 0
        disabled: List[str] = []
        msg = ("remediate", "settle") if settle else ("remediate",)
        for shard_id, payload in sorted(self._broadcast(*msg).items()):
            if payload is None:
                continue
            snap = payload["snapshot"]
            actions += snap["actions"]
            pending += snap["pending"]
            for name in snap["disabled"]:
                if name not in disabled:
                    disabled.append(name)
            for name, row in snap["playbooks"].items():
                agg = playbooks.setdefault(
                    name, {"actions": 0, "paid": 0, "unpaid": 0,
                           "disabled": False})
                agg["actions"] += row["actions"]
                agg["paid"] += row["paid"]
                agg["unpaid"] += row["unpaid"]
                agg["disabled"] = agg["disabled"] or bool(row["disabled"])
        return {"playbooks": playbooks, "actions_total": actions,
                "pending": pending, "disabled": sorted(disabled)}

    def slo_union(self) -> Dict[str, Any]:
        """Every live shard's alert state folded into one view: pages
        summed per objective, states keyed ``shNN:series``."""
        pages: Dict[str, int] = {}
        states: Dict[str, str] = {}
        transitions = 0
        dumps: List[str] = []
        for shard_id, payload in sorted(self._broadcast("slo").items()):
            if payload is None:
                continue
            for base, n in payload["pages"].items():
                pages[base] = pages.get(base, 0) + n
            for key, st in payload["states"].items():
                states[f"sh{shard_id:02d}:{key}"] = st
            transitions += payload["transitions"]
            dumps.extend(payload["flight_dumps"])
        return {"pages": pages, "states": states,
                "transitions": transitions, "flight_dumps": dumps}

    def goodput_union(self) -> Optional[Dict[str, Any]]:
        """The fleet goodput ledger as the UNION of every live shard's
        rows — unit ids are shard-prefixed, so the union digests exactly
        like ``fingerprint()`` does for object state. Conservation must
        hold per shard AND for the union (sums of exact sums)."""
        from kubeflow_tpu.obs.goodput import goodput_rows_digest

        rows: List[Tuple] = []
        cats: Dict[str, int] = {}
        tracked = 0
        conserved = True
        any_payload = False
        for shard_id, payload in self._broadcast("goodput").items():
            if payload is None:
                continue
            any_payload = True
            rows.extend(tuple(r) for r in payload["rows"])
            conserved = conserved and payload["conserved"]
            for cat, n in payload["categories_ticks"].items():
                cats[cat] = cats.get(cat, 0) + n
            tracked += payload["summary"]["tracked_ticks"]
        if not any_payload:
            return None
        conserved = conserved and sum(cats.values()) == tracked
        return {
            "categories_ticks": dict(sorted(cats.items())),
            "tracked_ticks": tracked,
            "conserved": conserved,
            "fingerprint": goodput_rows_digest(rows),
        }

    def shard_rows(self, shard_id: int) -> List[Tuple[str, str, str, str]]:
        return [tuple(r) for r in self._call(shard_id, "fingerprint")]

    def shard_fingerprint(self, shard_id: int) -> tuple:
        return signature_of_rows(self.shard_rows(shard_id))

    def fingerprint(self) -> tuple:
        """(per-kind phase counts, signature) over the UNION of every live
        shard's store — directly comparable to a serial run's
        ``state_fingerprint()``."""
        rows: List[Tuple[str, str, str, str]] = []
        for shard_id, shard in self._broadcast("fingerprint").items():
            rows.extend(tuple(r) for r in shard)
        return signature_of_rows(rows)

    def __enter__(self) -> "ShardedControlPlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------------
# Sharded sweep (the bench driver)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ShardedSweepReport:
    jobs: int
    shards: int
    workers: int
    rtt_s: float
    reconciles: int
    wall_s: float
    reconciles_per_sec: float
    all_succeeded: bool
    rounds: int
    jobs_per_shard: Dict[int, int]
    final_state: Dict[str, Dict[str, int]]
    state_signature: str

    def summary(self) -> Dict[str, object]:
        return {
            "jobs": self.jobs,
            "shards": self.shards,
            "workers": self.workers,
            "rtt_s": self.rtt_s,
            "reconciles": self.reconciles,
            "sweep_wall_s": round(self.wall_s, 3),
            "reconciles_per_sec": round(self.reconciles_per_sec, 1),
            "rounds": self.rounds,
            "jobs_per_shard": dict(self.jobs_per_shard),
            "final_state": {k: dict(v) for k, v in self.final_state.items()},
            "state_signature": self.state_signature,
        }


def fleet_docs(num_jobs: int, num_namespaces: int,
               slice_type: str = "v5e-16") -> List[Dict[str, Any]]:
    """The bench fleet as manifest dicts — byte-identical to the objects
    ``run_controlplane_sweep`` creates, so the sharded union fingerprint
    is directly comparable to the serial one."""
    return [
        {
            "kind": "TpuJob",
            "metadata": {"name": f"job-{i:04d}",
                         "namespace": f"ns-{i % num_namespaces:02d}"},
            "spec": {"sliceType": slice_type, "mesh": {"dp": -1},
                     "backoffSeconds": 0.0},
        }
        for i in range(num_jobs)
    ]


def host_cpu_headroom(sample_s: float = 0.5) -> float:
    """Measured aggregate multi-process CPU headroom of THIS host: the
    ratio of 2-process to 1-process spin throughput (1.0 = one effective
    core, 2.0 = two clean cores). Shared/throttled CI hosts commonly
    measure well under their advertised core count; the sharded bench
    records this next to its speedup so the number can be read against
    the ceiling the host actually offers."""
    import multiprocessing as mp
    import time as _time

    def spin(v):
        t0 = _time.perf_counter()
        x = 0
        while _time.perf_counter() - t0 < sample_s:
            x += 1
        v.value = x

    def run(nprocs: int) -> float:
        ctx = mp.get_context("fork" if "fork" in
                             mp.get_all_start_methods() else "spawn")
        vals = [ctx.Value("q", 0) for _ in range(nprocs)]
        procs = [ctx.Process(target=spin, args=(v,)) for v in vals]
        for p in procs:
            p.start()
        for p in procs:
            p.join()
        return float(sum(v.value for v in vals))

    solo = run(1)
    duo = run(2)
    return duo / solo if solo > 0 else 1.0


def run_sharded_sweep(
    *,
    num_jobs: int = 1000,
    num_namespaces: int = 20,
    shards: int = 4,
    workers: int = 1,
    rtt_s: float = 0.0,
    slice_type: str = "v5e-16",
    max_rounds: int = 12,
    state_dir: str = "",
    seed: int = 0,
    start_method: str = "fork",
) -> ShardedSweepReport:
    """Drive the standard bench fleet across ``shards`` shard processes to
    convergence. Fleet creation happens before the clock starts (matching
    the serial sweep, which also times only the reconcile phase).
    ``state_dir`` enables the per-shard WAL (off by default: the bench
    measures dispatch, the soak proves durability)."""
    if num_jobs < 1 or num_namespaces < 1:
        raise ValueError("num_jobs and num_namespaces must be >= 1")
    num_namespaces = min(num_namespaces, num_jobs)
    docs = fleet_docs(num_jobs, num_namespaces, slice_type)
    cp = ShardedControlPlane(
        shards, workers=workers, rtt_us=int(round(rtt_s * 1e6)),
        state_dir=state_dir, seed=seed, start_method=start_method,
    )
    try:
        created = cp.create(docs)
        reconciles = 0
        rounds = 0
        t0 = time.perf_counter()
        for _ in range(max_rounds):
            rounds += 1
            res = cp.round(30.0)
            reconciles += sum(r["reconciles"] for r in res.values())
            if all(r["terminal"] for r in res.values()):
                break
        wall = time.perf_counter() - t0
        counts, signature = cp.fingerprint()
    finally:
        cp.close()
    job_phases = counts.get("TpuJob", {})
    return ShardedSweepReport(
        jobs=num_jobs,
        shards=shards,
        workers=workers,
        rtt_s=rtt_s,
        reconciles=reconciles,
        wall_s=wall,
        reconciles_per_sec=reconciles / wall if wall > 0 else 0.0,
        all_succeeded=job_phases.get("Succeeded", 0) == num_jobs,
        rounds=rounds,
        jobs_per_shard=created,
        final_state=counts,
        state_signature=signature,
    )
