"""Deployment REST plane: the bootstrap/kfctl server, TPU-native.

The reference's click-to-deploy backend exposed deployment-as-a-service:
``POST /kfctl/apps/v1beta1/create`` spawned one kfctl server per
deployment which ran the apply engine asynchronously and served the
latest status via ``GET`` (reference: bootstrap/cmd/bootstrap/app/
router.go:275-405 — per-deployment StatefulSet; kfctlServer.go:43-46,
105-330 — channel + process() loop + mutex-guarded GetLatestKfDef;
expired deployments reaped by cmd/gc). The round-3 verdict called this
the one reference component with zero counterpart.

Here the same surface wraps the platform's own apply engine
(controlplane.platform.Platform — what ``tpuctl apply`` drives):

- ``POST   /kfctl/apps/v1beta1/create``           body: {name, spec?,
  resources?} — spec is a PlatformConfig spec, resources extra CR docs.
  Returns 202; the apply runs on a per-deployment worker thread (the
  in-process analogue of the per-deployment server pod — this platform's
  deployments are in-memory/state-dir platforms, not GCP projects, so a
  process boundary would add failure modes without isolation value).
- ``GET    /kfctl/apps/v1beta1/get/<name>``       mutex-guarded status
  copy: phase Pending|Applying|Ready|Failed, applied components, error.
- ``GET    /kfctl/apps/v1beta1/list``
- ``GET    /kfctl/apps/v1beta1/substrate/<name>`` what the cloud provider
  currently holds for the deployment (the delete-leak check's view) —
  includes pools a FAILED apply provisioned before its config stored.
- ``DELETE /kfctl/apps/v1beta1/delete/<name>``    teardown + state GC
  (substrate deprovision is leak-checked; a leak is a loud 500).

Re-POSTing an existing name re-applies idempotently (the reference's
repeated-apply contract, kfctl_second_apply.py:12-24).
"""

from __future__ import annotations

import copy
import os
import shutil
import threading
from typing import Dict, List, Optional

from kubeflow_tpu.controlplane.api.serde import from_dict
from kubeflow_tpu.controlplane.api.types import (
    PlatformConfig,
    PlatformConfigSpec,
)
from kubeflow_tpu.controlplane.platform import DEFAULT_COMPONENTS, Platform
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.webapps.router import (
    Html,
    JsonHttpServer,
    Request,
    RestError,
    Router,
)

log = get_logger("bootstrap")

_PREFIX = "/kfctl/apps/v1beta1"

# The click-to-deploy form (reference: gcp-click-to-deploy/src/
# DeployForm.tsx — deployment name + project/zone/version pickers, a
# Deploy button, and polled status). Same dependency-free vanilla-JS
# approach as webapps/frontend.py, over this server's own REST surface;
# every interpolation passes esc()/encodeURIComponent (the stored-XSS
# invariant tests/test_frontend_js.py enforces structurally).
_DEPLOY_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Deploy Kubeflow TPU</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; }
 table { border-collapse: collapse; margin: 1rem 0; min-width: 30rem; }
 td, th { border: 1px solid #ccc; padding: .3rem .6rem; text-align: left; }
 .phase-Ready { color: #0a7d32; }
 .phase-Failed { color: #b3261e; }
 fieldset { margin: 1rem 0; max-width: 40rem; }
 label { display: inline-block; margin: .2rem .8rem .2rem 0; }
</style></head>
<body>
<h1>Deploy Kubeflow TPU</h1>
<form id="deploy">
 <input id="name" placeholder="deployment name" required
        pattern="[a-z0-9]([-a-z0-9]*[a-z0-9])?">
 <label>Default slice:
  <select id="slice">__SLICES__</select></label>
 <fieldset><legend>Components</legend>__COMPONENTS__</fieldset>
 <button>Deploy</button>
</form>
<h2>Deployments</h2><div id="err" class="phase-Failed"></div>
<div id="list"></div>
<script>
const H = {'content-type': 'application/json'};
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({'&': '&amp;', '<': '&lt;',
    '>': '&gt;', '"': '&quot;', "'": '&#39;'})[c]);
}
async function api(path, opts) {
  const r = await fetch(path, opts);
  const data = await r.json();
  if (!r.ok) throw new Error(data.error || r.statusText);
  return data;
}
function showErr(e) {
  document.getElementById('err').textContent = e ? String(e.message || e)
                                                 : '';
}
let listErr = false;
async function refresh() {
  let out;
  // Only clear an error THIS list path set: a create/delete failure
  // rendered by the submit handler must survive the trailing refresh()
  // (found by the executed-page tier: the error flashed and vanished).
  try {
    out = await api('__PREFIX__/list');
    if (listErr) { showErr(''); listErr = false; }
  }
  catch (e) { showErr(e); listErr = true; return; }
  const list = document.getElementById('list');
  list.innerHTML = '<table><tr><th>name</th><th>phase</th>' +
    '<th>components</th><th>error</th><th></th></tr>' +
    out.deployments.map(d =>
      `<tr><td>${esc(d.name)}</td>` +
      `<td class="phase-${esc(d.phase)}">${esc(d.phase)}</td>` +
      `<td>${esc(d.components.length)}</td>` +
      `<td>${esc(d.error)}</td>` +
      `<td><button class="del" data-name="${esc(d.name)}">delete` +
      `</button></td></tr>`).join('') + '</table>';
  // Event delegation via dataset, no inline JS-string interpolation.
  list.querySelectorAll('button.del').forEach(b => b.onclick = async () => {
    try {
      await api('__PREFIX__/delete/' + encodeURIComponent(b.dataset.name),
                {method: 'DELETE'});
      showErr('');
    } catch (e) { showErr(e); listErr = false; }
    refresh();
  });
}
document.getElementById('deploy').onsubmit = async (e) => {
  e.preventDefault();
  const components = [...document.querySelectorAll('input.comp:checked')]
    .map(c => ({name: c.value, enabled: true}));
  // An empty components list means "use the defaults" to the engine
  // (Platform.apply_config), which would be the opposite of what a
  // deselect-everything click expresses — refuse it here.
  if (!components.length) {
    showErr('select at least one component');
    return;
  }
  try {
    await api('__PREFIX__/create', {method: 'POST', headers: H,
      body: JSON.stringify({
        name: document.getElementById('name').value,
        spec: {
          default_slice_type: document.getElementById('slice').value,
          components,
        },
      })});
    showErr('');
  } catch (err) { showErr(err); listErr = false; }
  refresh();
};
refresh(); setInterval(refresh, 2000);
</script></body></html>"""

def _deploy_page() -> str:
    from kubeflow_tpu.topology.slices import list_slices

    slices = "".join(
        f'<option{" selected" if s == "v5e-16" else ""}>{s}</option>'
        for s in list_slices()
    )
    comps = "".join(
        f'<label><input type="checkbox" class="comp" value="{c}" checked>'
        f"{c}</label>"
        for c in DEFAULT_COMPONENTS
    )
    return (_DEPLOY_PAGE
            .replace("__SLICES__", slices)
            .replace("__COMPONENTS__", comps)
            .replace("__PREFIX__", _PREFIX))


class _Deployment:
    def __init__(self, name: str):
        self.name = name
        self.phase = "Pending"
        self.error = ""
        self.components: List[str] = []
        self.platform: Optional[Platform] = None
        self.thread: Optional[threading.Thread] = None


class DeploymentServer:
    """The kfctl-server REST surface over per-deployment Platform engines.

    ``state_dir``: when set, each deployment persists under
    ``<state_dir>/<name>`` (tpuctl's state-backend layout, so
    ``tpuctl --state-dir <state_dir>/<name> get ...`` inspects it);
    delete removes the directory (the reference GC's job).
    """

    def __init__(self, *, state_dir: str = "",
                 host: str = "127.0.0.1", port: int = 0):
        self.state_dir = state_dir
        self._deployments: Dict[str, _Deployment] = {}
        self._lock = threading.Lock()
        self._http = JsonHttpServer(self.router(), host=host, port=port)
        self.port = self._http.port

    # ------------- engine -------------

    def _apply(self, dep: _Deployment, spec: dict, resources: list) -> None:
        try:
            with self._lock:
                dep.phase = "Applying"
            if dep.platform is None:
                if self.state_dir:
                    dep.platform = Platform.load(
                        os.path.join(self.state_dir, dep.name))
                else:
                    dep.platform = Platform()
            cfg = PlatformConfig(spec=from_dict(PlatformConfigSpec, spec))
            cfg.metadata.name = dep.name
            dep.platform.apply_config(cfg)
            for doc in resources:
                dep.platform.apply_resource(doc)
            dep.platform.reconcile()
            if self.state_dir:
                dep.platform.save(os.path.join(self.state_dir, dep.name))
            with self._lock:
                dep.phase = "Ready"
                dep.error = ""
                dep.components = list(dep.platform.components)
        except Exception as e:  # noqa: BLE001 — status carries the failure
            log.error("deployment apply failed",
                      kv={"name": dep.name, "err": repr(e)})
            with self._lock:
                dep.phase = "Failed"
                dep.error = f"{type(e).__name__}: {e}"

    # ------------- handlers -------------

    def _create(self, req: Request):
        name = req.body.get("name", "")
        if not name or "/" in name or name.startswith("."):
            raise RestError(400, "body.name must be a plain deployment name")
        spec = req.body.get("spec") or {}
        resources = req.body.get("resources") or []
        if not isinstance(resources, list):
            raise RestError(400, "body.resources must be a list of docs")
        with self._lock:
            dep = self._deployments.get(name)
            if dep is not None and dep.phase == "Applying":
                # One apply at a time per deployment (the reference
                # serialised via the per-server channel).
                raise RestError(409, f"deployment {name} is mid-apply")
            if dep is None:
                dep = _Deployment(name)
                self._deployments[name] = dep
        # Async apply: the reference's channel + process() goroutine.
        dep.thread = threading.Thread(
            target=self._apply, args=(dep, spec, resources), daemon=True)
        dep.thread.start()
        return 202, {"name": name, "phase": "Pending"}

    def _status(self, dep: _Deployment) -> dict:
        return {
            "name": dep.name,
            "phase": dep.phase,
            "components": list(dep.components),
            "error": dep.error,
        }

    def _get(self, req: Request):
        with self._lock:
            dep = self._deployments.get(req.params["name"])
            if dep is None:
                raise RestError(404,
                                f"no deployment {req.params['name']!r}")
            # Mutex-guarded copy (kfctlServer.GetLatestKfDef:74-77).
            return copy.deepcopy(self._status(dep))

    def _substrate(self, req: Request):
        """What the cloud currently holds for the deployment — the same
        provider view the delete-leak check reads, surfaced for operators
        (the reference's DM-resources listing)."""
        name = req.params["name"]
        with self._lock:
            dep = self._deployments.get(name)
        if dep is None:
            raise RestError(404, f"no deployment {name!r}")
        sub = (dep.platform.substrate_spec(name)
               if dep.platform is not None else None)
        if sub is None or not sub.provider:
            return {"name": name, "provider": "", "resources": []}
        from kubeflow_tpu.controlplane.substrate import get_provider

        return {"name": name, "provider": sub.provider,
                "resources": get_provider(sub.provider).list_resources(name)}

    def _list(self, req: Request):
        with self._lock:
            return {"deployments": [copy.deepcopy(self._status(d))
                                    for d in self._deployments.values()]}

    def _delete(self, req: Request):
        name = req.params["name"]
        with self._lock:
            dep = self._deployments.pop(name, None)
        if dep is None:
            raise RestError(404, f"no deployment {name!r}")
        if dep.thread is not None:
            dep.thread.join(timeout=30)
            if dep.thread.is_alive():
                # An in-flight apply could re-provision substrate AFTER
                # our deprovision passed its leak check — refuse rather
                # than race it.
                with self._lock:
                    self._deployments.setdefault(name, dep)
                raise RestError(
                    409, f"deployment {name} apply still running; retry")
        reclaimed = []
        if dep.platform is not None:
            from kubeflow_tpu.controlplane.substrate import SubstrateError

            try:
                # Substrate teardown with leak check (the reference's
                # kfctl delete contract): a leak is a loud 500, not a
                # silently-dropped deployment record.
                reclaimed = dep.platform.delete_config(name)
            except SubstrateError as e:
                with self._lock:
                    # setdefault: a concurrent create may have taken the
                    # name; never clobber the live record.
                    self._deployments.setdefault(name, dep)
                raise RestError(500, f"substrate teardown failed: {e}")
            dep.platform.manager.stop()
        if self.state_dir:
            shutil.rmtree(os.path.join(self.state_dir, name),
                          ignore_errors=True)
        return {"deleted": name, "substratePools": reclaimed}

    def router(self) -> Router:
        r = Router()
        # The click-to-deploy form (the reference SPA's job) over the same
        # REST surface.
        r.get("/", lambda q: Html(_deploy_page()))
        r.post(f"{_PREFIX}/create", self._create)
        r.get(f"{_PREFIX}/get/<name>", self._get)
        r.get(f"{_PREFIX}/substrate/<name>", self._substrate)
        r.get(f"{_PREFIX}/list", self._list)
        r.delete(f"{_PREFIX}/delete/<name>", self._delete)
        return r

    # ------------- lifecycle -------------

    def start(self) -> "DeploymentServer":
        self._http.start()
        log.info("deployment server up", kv={"port": self.port})
        return self

    def stop(self) -> None:
        self._http.stop()
        with self._lock:
            deps = list(self._deployments.values())
        for dep in deps:
            if dep.platform is not None:
                dep.platform.manager.stop()


def main(argv=None) -> int:
    import argparse
    import time

    p = argparse.ArgumentParser(prog="kftpu-bootstrap")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8085)
    p.add_argument("--state-dir", default="")
    args = p.parse_args(argv)
    server = DeploymentServer(state_dir=args.state_dir,
                              host=args.host, port=args.port).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
