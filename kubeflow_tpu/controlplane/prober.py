"""Availability prober: the platform-up SLO metric.

Rebuild of the reference's metric-collector (metric-collector/
service-readiness/kubeflow-readiness.py:20-37 — poll the deployment's
endpoint, export a 0/1 ``kubeflow_availability`` Prometheus gauge). Here
the prober is a platform component with pluggable probe targets:

- HTTP targets (``http_target``): GET an endpoint, healthy on 2xx — the
  reference's exact probe, pointed at kfam/JWA/serving ``/healthz``-style
  routes.
- Callable targets: any ``() -> bool``, e.g. in-process component checks
  or heartbeat freshness (``heartbeat_target``) so a wedged reconcile loop
  flips the platform unhealthy even while HTTP keeps answering.

Exports per-target ``kftpu_component_up{...}``-style gauges plus the
overall ``kftpu_availability`` 0/1 the reference's dashboards alerted on.
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import (
    Heartbeat,
    MetricsRegistry,
    global_registry,
    sanitize_metric_name,
)

log = get_logger("prober")

ProbeFn = Callable[[], bool]


def _target_gauge(registry: MetricsRegistry, name: str):
    """The per-target up/down gauge. Targets are named by operators
    ("kfam", "fake-kubelet", ...), so the interpolated fragment goes
    through sanitize_metric_name — `.replace('-', '_')` alone let a
    dotted target name reach the exposition illegally (KF103's harvest)."""
    return registry.gauge(
        # kftpu: allow(KF103): per-target name family
        # `kftpu_component_up_<target>` — sanitized here, documented as a
        # pattern row in docs/observability.md.
        f"kftpu_component_up_{sanitize_metric_name(name)}",
        f"1 when the {name} probe passes",
    )


def http_target(url: str, timeout: float = 5.0) -> ProbeFn:
    """Healthy when the endpoint answers 2xx (kubeflow-readiness.py:20-28)."""

    def probe() -> bool:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return 200 <= resp.status < 300
        except (urllib.error.URLError, OSError, ValueError):
            return False

    return probe


def heartbeat_target(hb: Heartbeat, max_age_s: float = 120.0) -> ProbeFn:
    """Healthy while the heartbeat is fresh — catches wedged loops."""

    def probe() -> bool:
        last = hb.last()
        return last > 0 and (time.time() - last) <= max_age_s

    return probe


def controller_target(manager, controller,
                      max_age_s: float = 120.0) -> ProbeFn:
    """Controller liveness: healthy when its heartbeat is fresh OR the
    manager has no work waiting (an idle controller legitimately never
    beats). A stale heartbeat WITH pending work = a wedged loop -> down.
    This is the non-tautological component probe the platform wires up."""

    def probe() -> bool:
        last = controller.heartbeat.last()
        if last > 0 and (time.time() - last) <= max_age_s:
            return True
        return manager.is_idle()

    return probe


class AvailabilityProber:
    def __init__(
        self,
        targets: Dict[str, ProbeFn],
        registry: MetricsRegistry = global_registry,
        *,
        interval_s: float = 30.0,
    ):
        self.targets = dict(targets)
        self.interval_s = interval_s
        # Guards targets/_gauges: add_target runs on caller threads while
        # the background loop iterates in probe().
        self._targets_lock = threading.Lock()
        self._gauges = {
            name: _target_gauge(registry, name)
            for name in self.targets
        }
        self.availability = registry.gauge(
            "kftpu_availability",
            "1 when every availability probe passes (the platform SLO "
            "gauge, reference kubeflow-readiness.py:29-37)",
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_probe = 0.0

    def add_target(self, name: str, probe: ProbeFn,
                   registry: MetricsRegistry = global_registry) -> None:
        gauge = _target_gauge(registry, name)
        with self._targets_lock:
            self.targets[name] = probe
            self._gauges[name] = gauge

    def probe(self) -> bool:
        """One probe pass over every target. Returns overall availability."""
        ok = True
        with self._targets_lock:
            # Snapshot: add_target mutates targets while this loop runs on
            # the background thread; iterating the live dict raced.
            items = list(self.targets.items())
            gauges = dict(self._gauges)
        for name, fn in items:
            try:
                up = bool(fn())
            except Exception as e:  # noqa: BLE001 — a probe must not kill the loop
                log.error("probe raised", kv={"target": name, "err": repr(e)})
                up = False
            gauges[name].set(1.0 if up else 0.0)
            if not up:
                ok = False
        self.availability.set(1.0 if ok else 0.0)
        self._last_probe = time.time()
        return ok

    def maybe_probe(self) -> None:
        """Rate-limited probe for callers on a hot path (Platform.reconcile):
        runs at most once per interval_s so slow HTTP targets don't tax
        every reconcile pass."""
        if time.time() - self._last_probe >= self.interval_s:
            self.probe()

    def start(self) -> "AvailabilityProber":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.probe()
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
