"""PodDefault admission mutation.

Mirrors components/admission-webhook/main.go:
- select PodDefaults whose selector matches the pod's labels (:69-95)
- conflict detection before applying anything (:98: safeToApplyPodDefaultsOnPod)
- inject env / volumes / volumeMounts / annotations / labels (:321-470)

Registered as an InMemoryApiServer mutator, the in-process seam equivalent
to the mutating-webhook HTTPS endpoint (:492-553).
"""

from __future__ import annotations

from typing import List, Optional

from kubeflow_tpu.controlplane.api.core import Pod
from kubeflow_tpu.controlplane.api.types import PodDefault
from kubeflow_tpu.controlplane.runtime.apiserver import InMemoryApiServer
from kubeflow_tpu.utils import get_logger

log = get_logger("poddefault-webhook")

APPLIED_ANNOTATION = "poddefaults.tpu.kubeflow.org/applied"


class PodDefaultConflictError(Exception):
    pass


def _matches(pd: PodDefault, pod: Pod) -> bool:
    sel = pd.spec.selector
    if not sel:
        return False
    return all(pod.metadata.labels.get(k) == v for k, v in sel.items())


def _check_conflicts(pod: Pod, defaults: List[PodDefault]) -> None:
    """Reject when two sources define the same key differently
    (reference safeToApplyPodDefaultsOnPod/mergeEnv semantics)."""
    env_sources = {}
    for c in pod.spec.containers:
        for e in c.env:
            env_sources[e.name] = e.value
    for pd in defaults:
        for e in pd.spec.env:
            if e.name in env_sources and env_sources[e.name] != e.value:
                raise PodDefaultConflictError(
                    f"env {e.name} conflicts (pod/{pd.metadata.name})"
                )
            env_sources[e.name] = e.value
    vol_sources = {v.name: v for v in pod.spec.volumes}
    for pd in defaults:
        for v in pd.spec.volumes:
            if v.name in vol_sources and vol_sources[v.name] != v:
                raise PodDefaultConflictError(
                    f"volume {v.name} conflicts (pod/{pd.metadata.name})"
                )
            vol_sources[v.name] = v


def mutate_pod(pod: Pod, defaults: List[PodDefault]) -> Pod:
    matched = [pd for pd in defaults if _matches(pd, pod)]
    if not matched:
        return pod
    _check_conflicts(pod, matched)
    for pd in matched:
        existing_env = {
            e.name for c in pod.spec.containers for e in c.env
        }
        for c in pod.spec.containers:
            c.env.extend(
                e for e in pd.spec.env if e.name not in existing_env
            )
            existing_mounts = {m.name for m in c.volume_mounts}
            c.volume_mounts.extend(
                m for m in pd.spec.volume_mounts
                if m.name not in existing_mounts
            )
        existing_vols = {v.name for v in pod.spec.volumes}
        pod.spec.volumes.extend(
            v for v in pd.spec.volumes if v.name not in existing_vols
        )
        for k, v in pd.spec.annotations.items():
            pod.metadata.annotations.setdefault(k, v)
        for k, v in pd.spec.labels.items():
            pod.metadata.labels.setdefault(k, v)
    pod.metadata.annotations[APPLIED_ANNOTATION] = ",".join(
        sorted(pd.metadata.name for pd in matched)
    )
    return pod


class PodDefaultMutator:
    """API-server admission hook: looks up PodDefaults in the pod's namespace
    at create time."""

    def __init__(self, api: InMemoryApiServer):
        self.api = api

    def __call__(self, obj):
        if getattr(obj, "kind", "") != "Pod":
            return obj
        defaults = self.api.list("PodDefault", namespace=obj.metadata.namespace)
        try:
            return mutate_pod(obj, defaults)
        except PodDefaultConflictError as e:
            # Admission rejection surfaces as a create error.
            raise
