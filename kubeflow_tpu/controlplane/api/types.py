"""The platform CRDs (group ``tpu.kubeflow.org``).

TPU-native rebuilds of the reference's CRs:
- TpuJob       — replaces TFJob + openmpi packaging (gang of workers on a
                 TPU slice; reference contract: TF_CONFIG wiring in
                 tf-controller-examples/tf-cnn/launcher.py:68-80 and the MPI
                 sidecar lifecycle, components/openmpi-controller/)
- Notebook     — components/notebook-controller/api/v1beta1/notebook_types.go:27-84
- Profile      — components/profile-controller/api/v1/profile_types.go:38-68
- PodDefault   — components/admission-webhook/pkg/apis/settings/v1alpha1/poddefault_types.go:27-87
- Tensorboard  — components/tensorboard-controller/api/v1alpha1/tensorboard_types.go:26-56
- PlatformConfig — the KfDef v1beta1 equivalent (bootstrap/cmd/bootstrap/
                 app/kfctlServer.go:23-27)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from kubeflow_tpu.controlplane.api.core import Container, EnvVar, Volume, VolumeMount
from kubeflow_tpu.controlplane.api.meta import Condition, ObjectMeta
from kubeflow_tpu.controlplane.api.serde import from_dict

GROUP = "tpu.kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"


# --------------------------------------------------------------------------
# TpuJob
# --------------------------------------------------------------------------

@dataclasses.dataclass
class MeshAxesSpec:
    """Logical parallelism request; validated against the slice topology by
    the controller via kubeflow_tpu.topology.plan_mesh."""

    dp: int = -1
    pp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1


@dataclasses.dataclass
class ElasticSpec:
    """Elastic gang bounds (VirtualFlow, arxiv 2009.09523): decouple the
    gang's logical size from the hardware it happens to hold. A TpuJob
    carrying this spec may RESIZE instead of restarting or failing:

    - on slice preemption the gang **shrinks** onto its surviving units
      (down to ``min_slices``) and resumes from the newest complete
      checkpoint — a resize (``status.resizes``), not a restart: no
      ``max_restarts`` consumed, no re-admission queueing, no backoff;
    - when the scheduler frees capacity the ElasticController **grows**
      the gang back toward ``max_slices`` (priority-ordered, never while
      same-type gangs queue unplaced);
    - initial placement shrinks to fit: a contended fleet places the
      gang at the widest width in [min_slices, num_slices] that fits.

    ``num_slices`` stays the preferred width and must sit inside
    [min_slices, max_slices]."""

    min_slices: int = 1
    max_slices: int = 1


@dataclasses.dataclass
class TpuJobSpec:
    slice_type: str = "v5e-16"
    num_slices: int = 1                 # >1 => multislice over DCN
    # Elastic bounds (None = fixed-size gang, the pre-elastic contract).
    elastic: Optional[ElasticSpec] = None
    mesh: MeshAxesSpec = dataclasses.field(default_factory=MeshAxesSpec)
    attn_impl: str = "full"             # full | flash | ring | ulysses | sp_auto
    # Workload: either a registry model (framework-run) or a custom image.
    model: str = ""                     # kubeflow_tpu.models registry name
    image: str = ""
    command: List[str] = dataclasses.field(default_factory=list)
    args: List[str] = dataclasses.field(default_factory=list)
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    # Checkpoint/resume contract (auto-resume on gang restart).
    checkpoint_dir: str = ""
    # Profiling: workers write jax.profiler traces here (surfaced by a
    # Tensorboard CR whose spec.trace_dir points at the same path).
    trace_dir: str = ""
    # Failure policy
    max_restarts: int = 3
    backoff_seconds: float = 10.0
    # What a slice preemption does to the gang: "restart" reschedules onto
    # surviving capacity WITHOUT consuming the max_restarts budget (the
    # preemption isn't the job's fault — VirtualFlow-style decoupling of
    # job from hardware); "fail" terminates the job on first preemption.
    preemption_policy: str = "restart"  # restart | fail
    # Scheduling
    priority: int = 0
    preemptible: bool = True


@dataclasses.dataclass
class TpuJobStatus:
    # Pending|Scheduling|Starting|Running|Restarting|Resizing|Succeeded|Failed
    phase: str = "Pending"
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    restarts: int = 0
    # Gang restarts caused by slice preemption — tracked separately from
    # ``restarts`` because they do not consume the max_restarts budget.
    preemptions: int = 0
    # Elastic resizes (shrink on preemption / grow on freed capacity) —
    # tracked next to ``preemptions``: a resize is a zero-downtime event,
    # not a restart, and consumes neither budget.
    resizes: int = 0
    # Elastic gangs: the logical width the gang currently runs at
    # (0 = spec.num_slices, the fixed-size contract).
    current_slices: int = 0
    # Pod names a committed resize still owes deletion (cleared once the
    # teardown completes). The ledger that lets the idempotent Resizing
    # re-entry tell ITS stale pods from a fresh eviction racing the
    # resize — fresh failures are classified, never swallowed.
    resize_doomed: List[str] = dataclasses.field(default_factory=list)
    # Final metrics reported by worker-0 via its termination message
    # (the K8s terminationMessagePath channel; consumed by the StudyJob
    # controller as the trial objective).
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    # worker name -> pod phase
    worker_states: Dict[str, str] = dataclasses.field(default_factory=dict)
    coordinator_address: str = ""
    slice_assignment: str = ""
    start_time: float = 0.0
    completion_time: float = 0.0
    last_restart_time: float = 0.0      # gates gang recreation by backoff
    resumed_from_step: int = -1


@dataclasses.dataclass
class TpuJob:
    api_version: str = API_VERSION
    kind: str = "TpuJob"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuJobSpec = dataclasses.field(default_factory=TpuJobSpec)
    status: TpuJobStatus = dataclasses.field(default_factory=TpuJobStatus)


# --------------------------------------------------------------------------
# Notebook
# --------------------------------------------------------------------------

@dataclasses.dataclass
class NotebookSpec:
    image: str = "kubeflow-tpu/jupyter:latest"
    cpu: str = "2"
    memory: str = "4Gi"
    # Single-host TPU attachment (e.g. "v5e-8"); empty = CPU-only notebook.
    tpu_slice: str = ""
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    volumes: List[Volume] = dataclasses.field(default_factory=list)
    volume_mounts: List[VolumeMount] = dataclasses.field(default_factory=list)
    # PodDefault labels to match (spawner "configurations",
    # jupyter-web-app .../utils.py:338-530)
    pod_defaults: List[str] = dataclasses.field(default_factory=list)
    # Spawn-from-checkpoint (Rok-variant analogue, rok/app.py:16-136):
    # the name of a TpuJob in this namespace whose orbax checkpoint the
    # notebook restores on start (controller injects KFTPU_RESTORE_DIR).
    checkpoint: str = ""


@dataclasses.dataclass
class NotebookStatus:
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    ready_replicas: int = 0
    container_state: str = ""
    last_activity: float = 0.0


@dataclasses.dataclass
class Notebook:
    api_version: str = API_VERSION
    kind: str = "Notebook"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: NotebookSpec = dataclasses.field(default_factory=NotebookSpec)
    status: NotebookStatus = dataclasses.field(default_factory=NotebookStatus)


# --------------------------------------------------------------------------
# Profile
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ProfilePluginSpec:
    """Cloud-integration plugin request (reference Plugin interface,
    profile_controller.go:74-80; e.g. workload identity
    plugin_workload_identity.go:44-166). Teardown is finalizer-guarded."""

    kind: str = ""                       # registered plugin name
    params: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProfileSpec:
    owner: str = ""                      # user email
    # TPU-chip quota (reference used generic ResourceQuotaSpec,
    # profile_controller.go:240-256). With `parent` set this is one
    # level of the HIERARCHICAL quota tree: a child's quota may never
    # exceed its parent's; siblings may over-commit (flagged).
    tpu_chip_quota: int = 0
    resource_quota: Dict[str, str] = dataclasses.field(default_factory=dict)
    plugins: List[ProfilePluginSpec] = dataclasses.field(default_factory=list)
    # Tenant tree (ISSUE 13): the parent Profile this tenant rolls up
    # under (org -> team -> user chains; "" = a root tenant) and its
    # fair-share weight among siblings — the weighted-DRF input the
    # gang scheduler and the serving LB arbitrate on.
    parent: str = ""
    weight: float = 1.0
    # Per-tenant goodput SLO (0 = none): the target productive fraction
    # of the tenant's attributed slice-seconds. The goodput ledger's
    # tenant rollup computes the burn rate `tpuctl tenants` alerts on.
    goodput_slo: float = 0.0


@dataclasses.dataclass
class ProfileStatus:
    phase: str = ""
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    # Plugins whose cloud-side grants are currently applied — the revoke
    # ledger: spec edits diff against this, so changing/removing a plugin
    # revokes the OLD grant instead of leaking it.
    applied_plugins: List[ProfilePluginSpec] = dataclasses.field(
        default_factory=list
    )


@dataclasses.dataclass
class Profile:
    api_version: str = API_VERSION
    kind: str = "Profile"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ProfileSpec = dataclasses.field(default_factory=ProfileSpec)
    status: ProfileStatus = dataclasses.field(default_factory=ProfileStatus)


# --------------------------------------------------------------------------
# PodDefault
# --------------------------------------------------------------------------

@dataclasses.dataclass
class PodDefaultSpec:
    # Pods whose labels match ALL of selector are mutated
    # (admission-webhook/main.go:69-95).
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    desc: str = ""
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    volumes: List[Volume] = dataclasses.field(default_factory=list)
    volume_mounts: List[VolumeMount] = dataclasses.field(default_factory=list)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodDefault:
    api_version: str = API_VERSION
    kind: str = "PodDefault"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodDefaultSpec = dataclasses.field(default_factory=PodDefaultSpec)


# --------------------------------------------------------------------------
# Tensorboard
# --------------------------------------------------------------------------

@dataclasses.dataclass
class TensorboardSpec:
    logspath: str = ""
    # Surfacing JAX profiler traces (SURVEY.md §5 Tracing: absent in the
    # reference, first-class here).
    trace_dir: str = ""


@dataclasses.dataclass
class TensorboardStatus:
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    ready: bool = False


@dataclasses.dataclass
class Tensorboard:
    api_version: str = API_VERSION
    kind: str = "Tensorboard"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TensorboardSpec = dataclasses.field(default_factory=TensorboardSpec)
    status: TensorboardStatus = dataclasses.field(
        default_factory=TensorboardStatus
    )


# --------------------------------------------------------------------------
# Serving (model inference as a platform workload)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class AutoscaleSpec:
    """Latency-driven horizontal autoscaling (ServingAutoscaler): keep
    scraped engine queue wait at ``target_queue_wait_s`` by scaling
    ``spec.replicas`` inside [min_replicas, max_replicas]. Scale-up is
    fast (every scrape over target); scale-down waits out a stabilization
    window (hysteresis) so a traffic dip can't thrash the fleet."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_queue_wait_s: float = 0.5


@dataclasses.dataclass
class ServingSpec:
    """Inference deployment surface (reference: TF-Serving deployments
    probed by testing/test_tf_serving.py:60-156). The pod runs
    kubeflow_tpu.serving.server against the KFTPU_SERVING_* env this
    controller injects; the engine shards over the requested mesh."""

    model: str = ""                     # kubeflow_tpu.models registry name
    slice_type: str = "v5e-8"
    # Engine sharding: slots (continuous-batch rows) over dp, heads over tp.
    mesh: MeshAxesSpec = dataclasses.field(
        default_factory=lambda: MeshAxesSpec(dp=-1)
    )
    # Horizontal scale-out: one engine pod per replica behind the Service
    # (the reference's TF-Serving-as-Deployment semantics,
    # testing/test_tf_serving.py:60-100). Scale-down drains: excess
    # replicas leave status.endpoints first, then get deleted.
    replicas: int = 1
    # Latency-driven replica autoscaling (None = fixed spec.replicas).
    autoscale: Optional[AutoscaleSpec] = None
    max_batch: int = 8
    max_len: int = 1024
    # Bounded admission: engine queue depth past which submit sheds with
    # 429 + Retry-After (0 = unbounded, the pre-PR-7 behaviour). The
    # depth watermark the LB's saturation shedding keys off.
    max_queue: int = 64
    # Paged KV-cache slots (ISSUE 12, serving/blocks.py): block size in
    # token positions and total pool size. 0 = engine defaults (block 16;
    # pool = max_batch x ceil(max_len / block) — the dense equivalent).
    # Sizing kv_blocks BELOW the dense equivalent oversubscribes slots
    # against actual request demand: admission then throttles on the
    # block free list instead of max_batch x max_len.
    kv_block_size: int = 0
    kv_blocks: int = 0
    decode_chunk: int = 8               # tokens per device dispatch
    # Engine compute/memory knobs (serving.engine.ServingConfig): int8
    # weight-only quantization is what lets an 8B model fit a 16G chip.
    quantize: str = ""                  # "" | "int8" (weights)
    quantize_kv: str = ""               # "" | "int8" (decode KV cache:
                                        # halves KV HBM -> bigger batches)
    param_dtype: str = "bfloat16"       # cast float params at engine start
    prefill_buckets: List[int] = dataclasses.field(default_factory=list)
    pipeline_depth: int = 0             # 0 = engine default
    logprobs: bool = False              # per-token logprobs in responses
                                        # (costs decode throughput; see
                                        # ServingConfig.logprobs)
    port: int = 8000
    image: str = "kubeflow-tpu/serving:latest"
    # Train->serve handoff: restore params from this TpuJob checkpoint dir
    # (empty = fresh init, dev/demo only).
    checkpoint_dir: str = ""
    # Path to a tokenizer.json (or a dir holding one) mounted in the pod:
    # enables the server's {"text": ...} request/response surface.
    tokenizer: str = ""


@dataclasses.dataclass
class ServingStatus:
    ready: bool = False                 # >= 1 replica serving
    phase: str = "Pending"
    endpoint: str = ""                  # VirtualService prefix once routed
    replicas: int = 0                   # pods that exist (incl. draining)
    ready_replicas: int = 0
    # Per-replica backend addresses ("host:port") of READY, non-draining
    # replicas — the load balancer's dispatch set. Draining replicas are
    # removed from here before their pod is deleted.
    endpoints: List[str] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Serving:
    api_version: str = API_VERSION
    kind: str = "Serving"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ServingSpec = dataclasses.field(default_factory=ServingSpec)
    status: ServingStatus = dataclasses.field(default_factory=ServingStatus)


# --------------------------------------------------------------------------
# StudyJob (HPO — the Katib equivalent)
# --------------------------------------------------------------------------

from kubeflow_tpu.hpo.space import ParameterSpec  # noqa: E402


@dataclasses.dataclass
class StudyJobSpec:
    """Katib StudyJob v1alpha1 surface (driven by the reference's
    testing/katib_studyjob_test.py:39-216), TPU-native: trials are TpuJobs,
    suggestions are deterministic pure functions (no vizier-core service),
    and metrics flow back through pod termination messages (no
    metrics-collector sidecar)."""

    objective: str = "loss"
    direction: str = "minimize"      # minimize | maximize
    algorithm: str = "random"        # kubeflow_tpu.hpo.ALGORITHMS
    max_trials: int = 10
    parallel_trials: int = 2
    seed: int = 0
    parameters: List[ParameterSpec] = dataclasses.field(default_factory=list)
    # Template cloned per trial; the suggestion lands in the worker env as
    # KFTPU_HPARAMS (JSON), consumed by train.runner's TrainConfig overrides.
    trial: TpuJobSpec = dataclasses.field(default_factory=TpuJobSpec)


@dataclasses.dataclass
class TrialRef:
    name: str = ""
    index: int = 0
    parameters: Dict[str, str] = dataclasses.field(default_factory=dict)
    phase: str = ""
    objective_value: Optional[float] = None


@dataclasses.dataclass
class StudyJobStatus:
    # Most-recent condition, katib-style (the reference test polls
    # status.condition for "Running"): Created|Running|Completed|Failed.
    condition: str = "Created"
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    trials: List[TrialRef] = dataclasses.field(default_factory=list)
    trials_running: int = 0
    trials_completed: int = 0
    trials_failed: int = 0
    best_trial: str = ""
    best_parameters: Dict[str, str] = dataclasses.field(default_factory=dict)
    best_objective: Optional[float] = None
    start_time: float = 0.0
    completion_time: float = 0.0


@dataclasses.dataclass
class StudyJob:
    api_version: str = API_VERSION
    kind: str = "StudyJob"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: StudyJobSpec = dataclasses.field(default_factory=StudyJobSpec)
    status: StudyJobStatus = dataclasses.field(default_factory=StudyJobStatus)


# --------------------------------------------------------------------------
# PlatformConfig (KfDef equivalent)
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ComponentConfig:
    name: str = ""
    enabled: bool = True
    params: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SlicePoolSpec:
    """A pool of TPU slices the substrate provider must create — the
    platform analogue of the reference's Deployment-Manager cluster
    resources (bootstrap/cmd/bootstrap/app/kfctlServer.go:219-296 runs
    Apply(PLATFORM) before Apply(K8S))."""

    name: str = ""
    slice_type: str = "v5e-16"     # topology.slices key
    num_slices: int = 1


@dataclasses.dataclass
class NodePoolSpec:
    """CPU node pool for the control plane / webapps."""

    name: str = ""
    machine_type: str = "n2-standard-8"
    count: int = 1


@dataclasses.dataclass
class SubstrateSpec:
    """Cloud-substrate provisioning request: which provider creates the
    TPU slice pools + node pools BEFORE the k8s-level apply. Provider
    implementations register in controlplane.substrate.PROVIDERS."""

    provider: str = ""             # "" = substrate already exists
    slice_pools: List[SlicePoolSpec] = dataclasses.field(
        default_factory=list)
    node_pools: List[NodePoolSpec] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PlatformConfigSpec:
    # Which controllers/services to run.
    components: List[ComponentConfig] = dataclasses.field(default_factory=list)
    # Default TPU topology section (SURVEY.md §5 Config: replaces GPU pickers).
    default_slice_type: str = "v5e-16"
    user_id_header: str = "x-goog-authenticated-user-email"
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    cluster_domain: str = "cluster.local"
    # Optional cloud-substrate half (Apply(PLATFORM)): provision slice/
    # node pools through a SubstrateProvider before components start.
    substrate: Optional[SubstrateSpec] = None


@dataclasses.dataclass
class PlatformConfigStatus:
    phase: str = ""
    applied_components: List[str] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PlatformConfig:
    api_version: str = API_VERSION
    kind: str = "PlatformConfig"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PlatformConfigSpec = dataclasses.field(
        default_factory=PlatformConfigSpec
    )
    status: PlatformConfigStatus = dataclasses.field(
        default_factory=PlatformConfigStatus
    )


# --------------------------------------------------------------------------
# Kind registry (for the API server and tpuctl YAML loading)
# --------------------------------------------------------------------------

from kubeflow_tpu.controlplane.api import core as _core  # noqa: E402

KIND_REGISTRY: Dict[str, type] = {
    "TpuJob": TpuJob,
    "Notebook": Notebook,
    "Profile": Profile,
    "PodDefault": PodDefault,
    "Tensorboard": Tensorboard,
    "Serving": Serving,
    "StudyJob": StudyJob,
    "PlatformConfig": PlatformConfig,
    "Pod": _core.Pod,
    "Service": _core.Service,
    "Namespace": _core.Namespace,
    "ServiceAccount": _core.ServiceAccount,
    "RoleBinding": _core.RoleBinding,
    "ResourceQuota": _core.ResourceQuota,
    "VirtualService": _core.VirtualService,
    "AuthorizationPolicy": _core.AuthorizationPolicy,
    "Event": _core.Event,
}


def object_from_dict(data: Dict[str, Any]):
    kind = data.get("kind", "")
    cls = KIND_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"unknown kind {kind!r}; known: {sorted(KIND_REGISTRY)}")
    return from_dict(cls, data)
