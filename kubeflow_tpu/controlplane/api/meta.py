"""Object metadata: the identity/ownership/lifecycle envelope every resource
carries (the analogue of k8s ObjectMeta as used throughout the reference's
CRD types, e.g. components/profile-controller/api/v1/profile_types.go:38-68).
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Dict, List, Optional


@dataclasses.dataclass
class OwnerReference:
    # metav1.OwnerReference requires apiVersion on a real apiserver; every
    # owner in this platform is one of our own CRs, so default the group.
    api_version: str = "tpu.kubeflow.org/v1alpha1"
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = True


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    owner_references: List[OwnerReference] = dataclasses.field(
        default_factory=list
    )
    finalizers: List[str] = dataclasses.field(default_factory=list)


def new_meta(name: str, namespace: str = "", **kw) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace, **kw)


def fresh_identity(meta: ObjectMeta) -> None:
    meta.uid = uuid.uuid4().hex
    meta.creation_timestamp = time.time()


@dataclasses.dataclass
class Condition:
    """Typed status condition (mirrors the reference's use of pod/CR
    conditions, notebook_controller.go:196-227)."""

    type: str = ""
    status: str = "Unknown"          # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


def set_condition(conditions: List[Condition], new: Condition) -> List[Condition]:
    """Upsert by type; bump transition time only when status changes."""
    out = []
    found = False
    for c in conditions:
        if c.type == new.type:
            found = True
            if c.status != new.status:
                new.last_transition_time = time.time()
            else:
                new.last_transition_time = c.last_transition_time
                c.reason, c.message = new.reason, new.message
                out.append(dataclasses.replace(c))
                continue
            out.append(new)
        else:
            out.append(c)
    if not found:
        new.last_transition_time = time.time()
        out.append(new)
    return out
