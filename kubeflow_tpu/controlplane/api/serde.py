"""Dataclass <-> dict round-trip with K8s-style camelCase keys.

Keeps the Python API snake_case while manifests/YAML stay camelCase, the
same convention the reference's Go types get from JSON struct tags
(e.g. components/notebook-controller/api/v1beta1/notebook_types.go:27-84).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


def _camel(s: str) -> str:
    parts = s.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _snake(s: str) -> str:
    out = []
    for ch in s:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def to_dict(obj: Any) -> Any:
    """Recursively convert dataclasses to camelCase dicts, dropping None and
    empty containers (K8s-manifest style: absent, not null)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = to_dict(getattr(obj, f.name))
            if v is None or v == {} or v == []:
                continue
            out[_camel(f.name)] = v
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items() if v is not None}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _resolve_type(tp: Any) -> Any:
    origin = get_origin(tp)
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        return args[0] if len(args) == 1 else None
    return tp


def from_dict(cls: Type[T], data: Optional[Dict[str, Any]]) -> T:
    """Recursively build a dataclass from a camelCase dict. Unknown keys are
    ignored (forward compatibility); missing keys fall back to defaults."""
    if data is None:
        data = {}
    if not dataclasses.is_dataclass(cls):
        return data  # type: ignore[return-value]
    hints = typing.get_type_hints(cls)
    kwargs: Dict[str, Any] = {}
    field_names = {f.name for f in dataclasses.fields(cls)}
    for key, value in data.items():
        name = _snake(key)
        if name not in field_names:
            continue
        tp = _resolve_type(hints.get(name))
        origin = get_origin(tp)
        if dataclasses.is_dataclass(tp) and isinstance(value, dict):
            kwargs[name] = from_dict(tp, value)
        elif origin in (list, tuple) and value is not None:
            (elem,) = get_args(tp) or (Any,)
            if dataclasses.is_dataclass(elem):
                kwargs[name] = [from_dict(elem, v) for v in value]
            else:
                kwargs[name] = list(value)
        else:
            kwargs[name] = value
    return cls(**kwargs)  # type: ignore[call-arg]
