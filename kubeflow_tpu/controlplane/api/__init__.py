from kubeflow_tpu.controlplane.api.meta import (
    ObjectMeta,
    Condition,
    OwnerReference,
    new_meta,
)
from kubeflow_tpu.controlplane.api.serde import to_dict, from_dict
from kubeflow_tpu.controlplane.api.core import (
    AuthorizationPolicy,
    Container,
    EnvVar,
    Namespace,
    Pod,
    PodSpec,
    PodStatus,
    ResourceQuota,
    RoleBinding,
    Service,
    ServiceAccount,
    VirtualService,
    VolumeMount,
    Volume,
)
from kubeflow_tpu.controlplane.api.types import (
    GROUP,
    Notebook,
    NotebookSpec,
    PlatformConfig,
    PodDefault,
    PodDefaultSpec,
    Profile,
    ProfileSpec,
    Tensorboard,
    TensorboardSpec,
    TpuJob,
    TpuJobSpec,
    KIND_REGISTRY,
    object_from_dict,
)

__all__ = [
    "ObjectMeta", "Condition", "OwnerReference", "new_meta",
    "to_dict", "from_dict",
    "AuthorizationPolicy",
    "Container", "EnvVar", "Namespace", "Pod", "PodSpec", "PodStatus",
    "ResourceQuota", "RoleBinding", "Service", "ServiceAccount",
    "VirtualService", "VolumeMount", "Volume",
    "GROUP", "Notebook", "NotebookSpec", "PlatformConfig",
    "PodDefault", "PodDefaultSpec", "Profile", "ProfileSpec",
    "Tensorboard", "TensorboardSpec", "TpuJob", "TpuJobSpec",
    "KIND_REGISTRY", "object_from_dict",
]
