"""Core (non-CRD) resources the controllers emit: Pods, Services, RBAC,
routing. Light typed mirrors of the K8s objects the reference's controllers
create (StatefulSet/Service/VirtualService in notebook_controller.go:278-435,
Namespace/SA/RoleBinding in profile_controller.go:121-239)."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from kubeflow_tpu.controlplane.api.meta import Condition, ObjectMeta


@dataclasses.dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclasses.dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclasses.dataclass
class Volume:
    name: str = ""
    # one of:
    empty_dir: Optional[dict] = None
    pvc: Optional[str] = None
    config_map: Optional[str] = None
    secret: Optional[str] = None


@dataclasses.dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = dataclasses.field(default_factory=list)
    args: List[str] = dataclasses.field(default_factory=list)
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    env_from: List[str] = dataclasses.field(default_factory=list)
    volume_mounts: List[VolumeMount] = dataclasses.field(default_factory=list)
    ports: List[int] = dataclasses.field(default_factory=list)
    # resource requests/limits, e.g. {"google.com/tpu": "4", "cpu": "8"}
    resources: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodSpec:
    containers: List[Container] = dataclasses.field(default_factory=list)
    volumes: List[Volume] = dataclasses.field(default_factory=list)
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    service_account: str = ""
    restart_policy: str = "Always"
    # TPU gang placement
    subdomain: str = ""
    hostname: str = ""
    scheduler_hints: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodStatus:
    phase: str = "Pending"   # Pending|Running|Succeeded|Failed
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    pod_ip: str = ""
    host_ip: str = ""
    node_name: str = ""
    message: str = ""
    # Container termination message (K8s terminationMessagePath channel);
    # workers write final metrics JSON here, surfaced by the kubelet.
    termination_message: str = ""


@dataclasses.dataclass
class Pod:
    api_version: str = "v1"
    kind: str = "Pod"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodSpec = dataclasses.field(default_factory=PodSpec)
    status: PodStatus = dataclasses.field(default_factory=PodStatus)


@dataclasses.dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0


@dataclasses.dataclass
class ServiceSpec:
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    ports: List[ServicePort] = dataclasses.field(default_factory=list)
    cluster_ip: str = ""      # "None" => headless (gang DNS)
    type: str = "ClusterIP"


@dataclasses.dataclass
class Service:
    api_version: str = "v1"
    kind: str = "Service"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ServiceSpec = dataclasses.field(default_factory=ServiceSpec)


@dataclasses.dataclass
class Namespace:
    api_version: str = "v1"
    kind: str = "Namespace"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    status: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ServiceAccount:
    api_version: str = "v1"
    kind: str = "ServiceAccount"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)


@dataclasses.dataclass
class Subject:
    kind: str = "User"
    name: str = ""


@dataclasses.dataclass
class RoleRef:
    kind: str = "ClusterRole"
    name: str = ""


@dataclasses.dataclass
class RoleBinding:
    api_version: str = "rbac.authorization.k8s.io/v1"
    kind: str = "RoleBinding"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    subjects: List[Subject] = dataclasses.field(default_factory=list)
    role_ref: RoleRef = dataclasses.field(default_factory=RoleRef)


@dataclasses.dataclass
class ResourceQuota:
    api_version: str = "v1"
    kind: str = "ResourceQuota"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    # e.g. {"google.com/tpu": "16"} — TPU chips instead of the reference's
    # generic hard limits (profile_controller.go:240-256)
    hard: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class HttpRoute:
    prefix: str = ""
    rewrite: str = ""
    destination_host: str = ""
    destination_port: int = 0


@dataclasses.dataclass
class VirtualService:
    """Istio-style route emitted for notebooks/tensorboards
    (notebook_controller.go:378-435)."""

    api_version: str = "networking.istio.io/v1beta1"
    kind: str = "VirtualService"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    gateways: List[str] = dataclasses.field(default_factory=list)
    hosts: List[str] = dataclasses.field(default_factory=list)
    http: List[HttpRoute] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Event:
    api_version: str = "v1"
    kind: str = "Event"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = "Normal"     # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1


@dataclasses.dataclass
class AuthorizationPolicy:
    """Modern Istio AuthorizationPolicy (replacing the reference's
    deprecated v1alpha3 ServiceRole/ServiceRoleBinding RBAC,
    profile_controller.go:188-194 / access-management/kfam/bindings.go:100-127;
    SURVEY.md §7 hardest-parts item 4)."""

    api_version: str = "security.istio.io/v1"
    kind: str = "AuthorizationPolicy"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    action: str = "ALLOW"
    # principals allowed (request.headers[<userid-header>] values)
    principals: List[str] = dataclasses.field(default_factory=list)
    user_id_header: str = "x-goog-authenticated-user-email"
