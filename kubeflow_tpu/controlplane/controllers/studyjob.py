"""StudyJob controller: hyperparameter studies as gangs of TpuJob trials.

The Katib axis of the platform (reference surface:
testing/katib_studyjob_test.py:39-216 — create a StudyJob, poll
status.condition until "Running"; katib's runtime was studyjob-controller
+ vizier-core suggestion gRPC + metrics-collector sidecars). TPU-native
redesign:

- No suggestion service: trial i's parameters are a pure function of
  (spec, i) (kubeflow_tpu.hpo.suggest), so reconcile can replay any
  trial's assignment from the spec — idempotent and restart-safe with
  zero suggestion state.
- No metrics-collector sidecar: workers report final metrics through the
  pod termination message (K8s terminationMessagePath), the TpuJob
  controller lifts worker-0's report into TpuJobStatus.metrics, and this
  controller reads the objective from there.
- Trials inherit all platform gates for free: TpuJob quota/capacity
  admission, gang restart, checkpoint auto-resume.
"""

from __future__ import annotations

import copy
import json
import time
from typing import List, Optional

from kubeflow_tpu.controlplane.api.core import EnvVar
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.api.types import StudyJob, TpuJob, TrialRef
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    EventRecorder,
    InMemoryApiServer,
    Result,
)
from kubeflow_tpu.hpo.space import encode, validate_space
from kubeflow_tpu.hpo.suggest import budget, suggest
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

STUDY_LABEL = "tpu.kubeflow.org/study-name"
TRIAL_INDEX_LABEL = "tpu.kubeflow.org/trial-index"


class StudyJobController(Controller):
    NAME = "studyjob"
    WATCH_KINDS = ("StudyJob", "TpuJob")

    def __init__(self, api: InMemoryApiServer,
                 registry: MetricsRegistry = global_registry):
        super().__init__(api, registry)
        self.recorder = EventRecorder(api, self.NAME)
        self.metrics_trials = registry.counter(
            "kftpu_study_trials_total", "Trial outcomes", ("outcome",)
        )

    @staticmethod
    def trial_name(study: str, index: int) -> str:
        return f"{study}-trial-{index}"

    # ------------- reconcile -------------

    def reconcile(self, namespace: str, name: str) -> Result:
        study = self.api.try_get("StudyJob", name, namespace)
        if study is None or study.metadata.deletion_timestamp is not None:
            return Result()
        if study.status.condition in ("Completed", "Failed"):
            return Result()

        try:
            validate_space(study.spec.parameters)
            n_budget = budget(study.spec.parameters, study.spec.algorithm,
                              study.spec.max_trials)
        except (ValueError, IndexError) as e:
            return self._fail(study, "InvalidSpace", str(e))
        if study.spec.parallel_trials < 1:
            return self._fail(
                study, "InvalidSpec",
                f"parallel_trials must be >= 1, got "
                f"{study.spec.parallel_trials}",
            )

        jobs = {
            j.metadata.labels.get(TRIAL_INDEX_LABEL, ""): j
            for j in self.reader.list(
                "TpuJob", namespace=namespace,
                label_selector={STUDY_LABEL: name},
                copy=False,
            )
        }

        prev_status = copy.deepcopy(study.status)
        trials: List[TrialRef] = []
        sign = -1.0 if study.spec.direction == "maximize" else 1.0
        history = []
        n_active = n_done = n_failed = 0
        for i in range(n_budget):
            job = jobs.get(str(i))
            if job is None:
                continue
            obj = job.status.metrics.get(study.spec.objective)
            ref = TrialRef(
                name=job.metadata.name, index=i,
                parameters=self._trial_params(study, i, job),
                phase=job.status.phase,
                objective_value=obj,
            )
            trials.append(ref)
            if job.status.phase == "Succeeded":
                n_done += 1
                history.append({
                    "parameters": dict(ref.parameters),
                    "objective": None if obj is None else sign * obj,
                })
            elif job.status.phase == "Failed":
                n_failed += 1
            else:
                n_active += 1

        # Spawn until the parallelism window is full or the budget is spent.
        # Iterate every unspawned index (not just past the max): a deleted
        # trial leaves a hole that must be respawned or the study would
        # never reach its budget and hang in Running forever.
        for i in range(n_budget):
            if n_active >= study.spec.parallel_trials:
                break
            if str(i) in jobs:
                continue
            if not self._spawn_trial(study, i, history):
                # Trial name squatted by a TpuJob this study doesn't own:
                # retrying every reconcile would hang the study in Running
                # forever with phantom trials. Fail loudly instead.
                return self._fail(
                    study, "TrialNameConflict",
                    f"TpuJob {self.trial_name(study.metadata.name, i)!r} "
                    f"exists and is not owned by this study",
                )
            self.metrics_trials.inc(outcome="spawned")
            n_active += 1

        # ---- status aggregation (katib-style single condition) ----
        st = study.status
        st.trials_running = n_active
        st.trials_completed = n_done
        st.trials_failed = n_failed
        st.trials = trials
        scored = [t for t in trials if t.objective_value is not None
                  and t.phase == "Succeeded"]
        if scored:
            best = min(scored, key=lambda t: sign * t.objective_value)
            st.best_trial = best.name
            st.best_parameters = dict(best.parameters)
            st.best_objective = best.objective_value
        finished = n_done + n_failed
        if finished >= n_budget:
            st.condition = "Failed" if n_done == 0 else "Completed"
            if st.completion_time == 0.0:
                st.completion_time = time.time()
                self.recorder.event(
                    study, "Normal", f"Study{st.condition}",
                    f"{n_done}/{n_budget} trials succeeded; best="
                    f"{st.best_trial or 'n/a'}",
                )
        elif n_active > 0:
            st.condition = "Running"
            if st.start_time == 0.0:
                st.start_time = time.time()
        st.conditions = set_condition(
            st.conditions,
            Condition(
                type="Running",
                status="True" if st.condition == "Running" else "False",
                reason=st.condition,
                message=(f"{n_done} done, {n_failed} failed, "
                         f"{n_active} active of {n_budget}"),
            ),
        )
        if st != prev_status:
            self.api.update_status(study)
        return Result()

    # ------------- trial spawning -------------

    def _trial_params(self, study: StudyJob, index: int,
                      job: Optional[TpuJob] = None) -> dict:
        # The assignment pinned in the job env at spawn time is
        # authoritative (history-steered algorithms can't be replayed);
        # fall back to recomputation only for algorithm-deterministic cases.
        if job is not None:
            for ev in job.spec.env:
                if ev.name == "KFTPU_HPARAMS":
                    return {k: str(v)
                            for k, v in json.loads(ev.value).items()}
        return encode(suggest(study.spec.parameters, study.spec.algorithm,
                              study.spec.seed, index))

    def _spawn_trial(self, study: StudyJob, index: int,
                     history: List[dict]) -> bool:
        """Create trial ``index``'s TpuJob. Returns False when the name is
        taken by a job that does not belong to this study."""
        assignment = suggest(
            study.spec.parameters, study.spec.algorithm,
            study.spec.seed, index, history,
        )
        spec = copy.deepcopy(study.spec.trial)
        spec.env = list(spec.env) + [
            EnvVar("KFTPU_HPARAMS", json.dumps(assignment)),
            EnvVar("KFTPU_TRIAL_INDEX", str(index)),
        ]
        if spec.checkpoint_dir:
            spec.checkpoint_dir = f"{spec.checkpoint_dir}/trial-{index}"
        name = self.trial_name(study.metadata.name, index)
        job = TpuJob(
            metadata=ObjectMeta(
                name=name,
                namespace=study.metadata.namespace,
                labels={
                    STUDY_LABEL: study.metadata.name,
                    TRIAL_INDEX_LABEL: str(index),
                },
                owner_references=[OwnerReference(
                    kind="StudyJob", name=study.metadata.name,
                    uid=study.metadata.uid,
                )],
            ),
            spec=spec,
        )
        existing = self.api.try_get("TpuJob", name, study.metadata.namespace)
        if existing is not None:
            return (existing.metadata.labels.get(STUDY_LABEL)
                    == study.metadata.name)
        self.api.create(job)
        self.recorder.event(
            study, "Normal", "TrialCreated",
            f"trial {index}: {encode(assignment)}",
        )
        return True

    def _fail(self, study: StudyJob, reason: str, msg: str) -> Result:
        study.status.condition = "Failed"
        study.status.conditions = set_condition(
            study.status.conditions,
            Condition(type="Running", status="False",
                      reason=reason, message=msg),
        )
        self.api.update_status(study)
        self.recorder.event(study, "Warning", reason, msg)
        return Result()
