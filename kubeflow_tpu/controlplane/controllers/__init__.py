from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
from kubeflow_tpu.controlplane.controllers.notebook import NotebookController
from kubeflow_tpu.controlplane.controllers.profile import ProfileController
from kubeflow_tpu.controlplane.controllers.tensorboard import TensorboardController
from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
from kubeflow_tpu.controlplane.controllers.studyjob import StudyJobController
from kubeflow_tpu.controlplane.controllers.serving import ServingController
from kubeflow_tpu.controlplane.controllers.autoscaler import ServingAutoscaler
from kubeflow_tpu.controlplane.webhook.poddefault import (
    PodDefaultMutator,
    mutate_pod,
)

__all__ = [
    "TpuJobController",
    "NotebookController",
    "ProfileController",
    "TensorboardController",
    "FakeKubelet",
    "StudyJobController",
    "ServingController",
    "ServingAutoscaler",
    "PodDefaultMutator",
    "mutate_pod",
]
