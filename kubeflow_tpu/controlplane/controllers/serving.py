"""Serving controller: Serving CR -> Pod + Service + VirtualService.

Model inference as a first-class platform workload — the reference reaches
it with hand-applied TF-Serving Deployments that its CI probes over
REST/gRPC (testing/test_tf_serving.py:60-156, deploy -> wait ready ->
query -> assert). Here the same lifecycle is a CRD:

- The pod runs ``python -m kubeflow_tpu.serving.server`` against the
  KFTPU_SERVING_* env injected below (model, mesh, engine limits, port) —
  the serving analogue of the TpuJob controller's KFTPU_* train contract.
- ``spec.replicas`` engine pods (``<name>-serving-<i>``) behind one
  Service — the reference's TF-Serving-as-a-Deployment shape
  (testing/test_tf_serving.py:60-100). Each ready replica's address lands
  in ``status.endpoints`` (the serving.lb dispatch set); scale-down is
  graceful: the excess replica leaves ``status.endpoints`` first, then is
  deleted after ``drain_grace_s`` so in-flight requests finish.
- ClusterIP service + VirtualService route ``/serving/<ns>/<name>/`` (the
  notebook controller's routing pattern, notebook_controller.go:378-435).
- Pod phases mirror into status.ready/ready_replicas/conditions; failed
  replicas are recreated (serving pods must always run).

Single-host slices only for now: multi-host sharded serving is a gang
concern (TpuJob's machinery) and the engine's mesh is per-process.
"""

from __future__ import annotations

import json
import time

from kubeflow_tpu.controlplane.api.core import (
    Container,
    EnvVar,
    HttpRoute,
    Pod,
    PodSpec,
    Service,
    ServicePort,
    ServiceSpec,
    VirtualService,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.api.types import Serving
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    EventRecorder,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.topology import get_slice
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry


class ServingController(Controller):
    NAME = "serving"
    WATCH_KINDS = ("Serving", "Pod")

    DRAIN_ANNOTATION = "serving.kubeflow.org/drain-since"

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        istio_gateway: str = "kubeflow/kubeflow-gateway",
        drain_grace_s: float = 15.0,
    ):
        super().__init__(api, registry)
        self.istio_gateway = istio_gateway
        self.drain_grace_s = drain_grace_s
        self.recorder = EventRecorder(api, self.NAME)
        self.metrics_ready = registry.gauge(
            "kftpu_serving_ready", "Ready serving deployments"
        )

    def reconcile(self, namespace: str, name: str) -> Result:
        sv = self.api.try_get("Serving", name, namespace)
        if sv is None or sv.metadata.deletion_timestamp is not None:
            return Result()

        err = self._validate(sv)
        if err:
            sv.status.phase = "Failed"
            sv.status.ready = False
            sv.status.conditions = set_condition(
                sv.status.conditions,
                Condition(type="Ready", status="False",
                          reason="InvalidSpec", message=err),
            )
            self._sync_status(sv)
            self.recorder.event(sv, "Warning", "InvalidSpec", err)
            return Result()

        def contract(pod):
            """Only the controller-owned slice of the container: admission
            mutators (PodDefault) may append env — that must not read as
            drift or the pod would delete/recreate forever."""
            c = pod.spec.containers[0]
            own = {e.name: e.value for e in c.env
                   if e.name.startswith("KFTPU_SERVING_")}
            return (own, c.image, tuple(c.ports))

        desired = max(1, sv.spec.replicas)
        live_pods = []
        for i in range(desired):
            pod_name = f"{name}-serving-{i}"
            live_pod = self.api.try_get("Pod", pod_name, namespace)
            desired_pod = self._pod(sv, pod_name, i)
            if (live_pod is not None
                    and contract(live_pod) != contract(desired_pod)):
                # Spec drift (port/model/engine limits): the env contract
                # is baked into the process, so the pod must be replaced —
                # leaving it would keep routing pointed at a stale server
                # while status reports Ready.
                self.api.delete("Pod", pod_name, namespace)
                self.recorder.event(sv, "Normal", "Recreated",
                                    f"pod {pod_name}: spec changed")
                live_pod = None
            elif (live_pod is not None
                    and live_pod.status.phase in ("Failed", "Succeeded")):
                # A serving replica must always run: recreate on exit (the
                # Deployment-controller restart semantics the reference
                # relied on for TF-Serving pods).
                self.api.delete("Pod", pod_name, namespace)
                self.recorder.event(
                    sv, "Warning", "Restarted",
                    f"pod {pod_name}: {live_pod.status.phase} "
                    f"({live_pod.status.message})")
                live_pod = None
            if live_pod is None:
                self.api.create(desired_pod)
                self.recorder.event(sv, "Normal", "Created",
                                    f"pod {pod_name}")
                live_pod = self.api.get("Pod", pod_name, namespace)
            live_pods.append(live_pod)

        # Scale-down drain: replicas beyond ``desired`` first disappear
        # from status.endpoints (this reconcile), then are deleted once
        # drain_grace_s has passed — in-flight requests on the LB finish.
        requeue = None
        now = time.time()
        for pod in self.api.list("Pod", namespace):
            owners = [o for o in pod.metadata.owner_references
                      if o.kind == "Serving" and o.name == name]
            if not owners or pod.metadata.deletion_timestamp is not None:
                continue
            prefix = f"{name}-serving-"
            if not pod.metadata.name.startswith(prefix):
                continue
            try:
                ordinal = int(pod.metadata.name[len(prefix):])
            except ValueError:
                continue
            if ordinal < desired:
                continue
            since = pod.metadata.annotations.get(self.DRAIN_ANNOTATION)
            if since is None:
                pod.metadata.annotations[self.DRAIN_ANNOTATION] = str(now)
                self.api.update(pod)
                self.recorder.event(sv, "Normal", "Draining",
                                    f"pod {pod.metadata.name}")
                requeue = self.drain_grace_s
            elif now - float(since) >= self.drain_grace_s:
                self.api.delete("Pod", pod.metadata.name, namespace)
                self.recorder.event(sv, "Normal", "ScaledDown",
                                    f"pod {pod.metadata.name}")
            else:
                requeue = max(0.05, float(since) + self.drain_grace_s - now)

        create_or_update(self.api, self._service(sv))
        create_or_update(self.api, self._virtual_service(sv))

        ready_pods = [p for p in live_pods if p.status.phase == "Running"]
        ready = len(ready_pods) > 0
        worst = next((p for p in live_pods if p.status.phase != "Running"),
                     None)
        sv.status.phase = "Ready" if ready else live_pods[0].status.phase
        sv.status.ready = ready
        sv.status.replicas = len(live_pods)
        sv.status.ready_replicas = len(ready_pods)
        sv.status.endpoints = [
            f"{p.status.pod_ip}:{self._replica_port(sv, i)}"
            for i, p in enumerate(live_pods)
            if p.status.phase == "Running" and p.status.pod_ip
        ]
        sv.status.endpoint = (
            f"/serving/{namespace}/{name}/" if ready else ""
        )
        sv.status.conditions = set_condition(
            sv.status.conditions,
            Condition(
                type="Ready", status="True" if ready else "False",
                reason=("AllReplicasReady" if len(ready_pods) == desired
                        else (worst.status.phase if worst else "Pending")),
                message=(worst.status.message if worst else
                         f"{len(ready_pods)}/{desired} replicas ready"),
            ),
        )
        self._sync_status(sv)
        self.metrics_ready.set(float(sum(
            1 for s in self.reader.list("Serving", copy=False)
            if s.status.ready
        )))
        return Result(requeue_after=requeue)

    def _validate(self, sv: Serving) -> str:
        # Imported at first validation, not module import: the registry
        # pulls in every model family (and JAX behind them) — dead weight
        # for control-plane processes (shard workers, tpuctl) that never
        # see a Serving CR.
        from kubeflow_tpu.models import list_models

        if sv.spec.model not in list_models():
            return (f"unknown model {sv.spec.model!r}; known: "
                    f"{sorted(list_models())}")
        try:
            st = get_slice(sv.spec.slice_type)
        except (KeyError, ValueError) as e:
            return f"unknown slice_type {sv.spec.slice_type!r}: {e}"
        if st.num_hosts != 1:
            return (f"serving slice must be single-host, {st.name} has "
                    f"{st.num_hosts} hosts")
        if sv.spec.replicas < 1:
            return f"replicas must be >= 1, got {sv.spec.replicas}"
        if sv.spec.quantize_kv not in ("", "int8"):
            return (f"unknown quantize_kv {sv.spec.quantize_kv!r}; "
                    "supported: '' (kv in the activation dtype), 'int8'")
        if sv.spec.quantize not in ("", "int8"):
            return (f"unknown quantize {sv.spec.quantize!r}; "
                    "supported: '', 'int8'")
        if sv.spec.pipeline_depth < 0:
            return f"pipeline_depth must be >= 0, got {sv.spec.pipeline_depth}"
        if sv.spec.max_queue < 0:
            return f"max_queue must be >= 0, got {sv.spec.max_queue}"
        if sv.spec.kv_block_size < 0:
            return (f"kv_block_size must be >= 0, "
                    f"got {sv.spec.kv_block_size}")
        if sv.spec.kv_blocks < 0:
            return f"kv_blocks must be >= 0, got {sv.spec.kv_blocks}"
        if sv.spec.kv_blocks:
            block = sv.spec.kv_block_size or 16
            if sv.spec.kv_blocks * block < sv.spec.max_len:
                return (
                    f"kv_blocks {sv.spec.kv_blocks} x block "
                    f"{block} = {sv.spec.kv_blocks * block} tokens "
                    f"cannot hold even one max_len={sv.spec.max_len} "
                    "sequence — nothing could ever admit")
        a = sv.spec.autoscale
        if a is not None:
            if a.min_replicas < 1:
                return (f"autoscale.min_replicas must be >= 1, "
                        f"got {a.min_replicas}")
            if a.max_replicas < a.min_replicas:
                return (f"autoscale.max_replicas {a.max_replicas} < "
                        f"min_replicas {a.min_replicas}")
            if a.target_queue_wait_s <= 0:
                return (f"autoscale.target_queue_wait_s must be > 0, "
                        f"got {a.target_queue_wait_s}")
        if any(b <= 0 for b in sv.spec.prefill_buckets):
            return f"prefill_buckets must be positive: {sv.spec.prefill_buckets}"
        return ""

    def _sync_status(self, sv) -> None:
        live = self.api.try_get("Serving", sv.metadata.name,
                                sv.metadata.namespace)
        if live is not None and live.status != sv.status:
            live.status = sv.status
            self.api.update_status(live)

    # ------------- emitted objects -------------

    def _owner(self, sv) -> OwnerReference:
        return OwnerReference(kind="Serving", name=sv.metadata.name,
                              uid=sv.metadata.uid)

    def _replica_port(self, sv: Serving, ordinal: int) -> int:
        """Per-replica port = spec.port + ordinal. On a real cluster every
        pod would get its own IP and bind spec.port; the process-kubelet
        substrate runs replicas on one flat host network, so the ordinal
        offset keeps them from colliding — and the offset is harmless on
        per-pod-IP networks too."""
        return sv.spec.port + ordinal

    def _pod(self, sv: Serving, pod_name: str, ordinal: int = 0) -> Pod:
        ns, name = sv.metadata.namespace, sv.metadata.name
        st = get_slice(sv.spec.slice_type)
        mesh = {a: v for a, v in vars(sv.spec.mesh).items() if v != 1}
        port = self._replica_port(sv, ordinal)
        env = [
            EnvVar("KFTPU_SERVING_MODEL", sv.spec.model),
            EnvVar("KFTPU_SERVING_MESH", json.dumps(mesh)),
            EnvVar("KFTPU_SERVING_PORT", str(port)),
            EnvVar("KFTPU_SERVING_MAX_BATCH", str(sv.spec.max_batch)),
            EnvVar("KFTPU_SERVING_MAX_LEN", str(sv.spec.max_len)),
            EnvVar("KFTPU_SERVING_DECODE_CHUNK", str(sv.spec.decode_chunk)),
        ]
        # Bounded admission (ISSUE 7): the engine's queue cap rides the
        # env contract so the replica sheds with 429 + Retry-After at
        # spec.max_queue waiting requests — and /healthz reports the
        # bound as the LB's saturation watermark. 0 = unbounded.
        if sv.spec.max_queue:
            env.append(EnvVar("KFTPU_SERVING_MAX_QUEUE",
                              str(sv.spec.max_queue)))
        # Paged KV-cache sizing (ISSUE 12): only when set, so existing
        # pods keep the engine's dense-equivalent defaults untouched.
        if sv.spec.kv_block_size:
            env.append(EnvVar("KFTPU_SERVING_KV_BLOCK_SIZE",
                              str(sv.spec.kv_block_size)))
        if sv.spec.kv_blocks:
            env.append(EnvVar("KFTPU_SERVING_KV_BLOCKS",
                              str(sv.spec.kv_blocks)))
        # Engine knobs ride the env contract only when set so existing
        # pods (and their drift contract) are untouched by the defaults.
        if sv.spec.quantize:
            env.append(EnvVar("KFTPU_SERVING_QUANTIZE", sv.spec.quantize))
        if sv.spec.quantize_kv:
            env.append(EnvVar("KFTPU_SERVING_QUANTIZE_KV",
                              sv.spec.quantize_kv))
        if sv.spec.param_dtype != "bfloat16":
            env.append(EnvVar("KFTPU_SERVING_PARAM_DTYPE",
                              sv.spec.param_dtype))
        if sv.spec.prefill_buckets:
            env.append(EnvVar(
                "KFTPU_SERVING_PREFILL_BUCKETS",
                ",".join(str(b) for b in sv.spec.prefill_buckets)))
        if sv.spec.pipeline_depth:
            env.append(EnvVar("KFTPU_SERVING_PIPELINE_DEPTH",
                              str(sv.spec.pipeline_depth)))
        if sv.spec.logprobs:
            env.append(EnvVar("KFTPU_SERVING_LOGPROBS", "1"))
        if getattr(sv.spec, "tokenizer", ""):
            env.append(EnvVar("KFTPU_SERVING_TOKENIZER",
                              sv.spec.tokenizer))
        if sv.spec.checkpoint_dir:
            env.append(EnvVar("KFTPU_SERVING_CHECKPOINT_DIR",
                              sv.spec.checkpoint_dir))
        return Pod(
            metadata=ObjectMeta(
                name=pod_name, namespace=ns,
                # Controller-owned selector label wins over user labels —
                # a user-set "serving-name" must not break Service routing.
                labels={**sv.metadata.labels, "serving-name": name},
                owner_references=[self._owner(sv)],
            ),
            spec=PodSpec(
                containers=[Container(
                    name="serving", image=sv.spec.image, env=env,
                    command=["python", "-m", "kubeflow_tpu.serving.server"],
                    ports=[port],
                    resources={st.resource_name(): str(st.chips_per_host)},
                )],
                node_selector=st.node_selectors(),
                service_account="default-editor",
            ),
        )

    def _service(self, sv: Serving) -> Service:
        name, ns = sv.metadata.name, sv.metadata.namespace
        return Service(
            metadata=ObjectMeta(name=f"{name}-serving", namespace=ns,
                                owner_references=[self._owner(sv)]),
            spec=ServiceSpec(
                selector={"serving-name": name},
                ports=[ServicePort(name="http", port=80,
                                   target_port=sv.spec.port)],
            ),
        )

    def _virtual_service(self, sv: Serving) -> VirtualService:
        name, ns = sv.metadata.name, sv.metadata.namespace
        return VirtualService(
            metadata=ObjectMeta(name=f"serving-{name}", namespace=ns,
                                owner_references=[self._owner(sv)]),
            gateways=[self.istio_gateway],
            hosts=["*"],
            http=[HttpRoute(
                prefix=f"/serving/{ns}/{name}/", rewrite="/",
                destination_host=f"{name}-serving.{ns}.svc.cluster.local",
                destination_port=80,
            )],
        )
