"""ServingAutoscaler: queue-wait-driven replica scaling for Serving CRs.

Closes the loop the PR-4 observability layer opened: the serving engines
already export queue-wait percentiles (``ServingEngine.load`` via
``/healthz``; ``kftpu_serving_queue_wait_seconds``), but nothing actuated
on them — replicas were whatever ``spec.replicas`` said when the CR was
applied. This controller reconciles ``Serving.spec.autoscale{min_replicas,
max_replicas, target_queue_wait_s}`` against scraped per-replica load and
rewrites ``spec.replicas``; the ServingController then creates/drains pods
and the LB follows ``status.endpoints`` — observe → decide → actuate, the
dynamic-scheduling shape of arxiv 1908.08082 applied to the serving fleet.

Control law (deliberately asymmetric — overload hurts immediately,
idle capacity only costs money):

- **Scale-up, fast**: any scrape whose worst replica p95 queue wait
  exceeds the target scales up proportionally
  (``ceil(replicas * wait / target)``, at least +1, clamped to max) —
  one decision per scrape interval, no damping.
- **Scale-down, slow**: the signal must sit below half the target (the
  hysteresis band) with idle queues for a full
  ``scale_down_stabilization_s`` window before ONE replica is removed,
  and the window restarts after every step — a traffic dip can't thrash
  the fleet through drain/recreate cycles.
- **Bounds always win**: replicas outside [min, max] are clamped even
  when the latency signal is quiet (reasons ``min-replicas`` /
  ``max-replicas``).

Every decision emits one ``autoscale.decision`` span LINKED to the
``autoscale.scrape`` span that triggered it (the same causal-link pattern
the reconcile kernel uses for write→reconcile edges), plus a
``kftpu_autoscaler_replicas{reason}`` counter of replicas added/removed;
the controller's reconcile histograms surface in ``tpuctl top`` like any
other controller's.
"""

from __future__ import annotations

import json
import math
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from kubeflow_tpu.controlplane.runtime import (
    Controller,
    EventRecorder,
    InMemoryApiServer,
    Result,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry
from kubeflow_tpu.utils.tracing import Tracer, global_tracer

#: Scale-down hysteresis: the signal must sit below this fraction of the
#: target (with empty queues) for the whole stabilization window.
SCALE_DOWN_BAND = 0.5


class ServingAutoscaler(Controller):
    NAME = "serving-autoscaler"
    WATCH_KINDS = ("Serving",)

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        tracer: Tracer = global_tracer,
        interval_s: float = 10.0,
        scale_down_stabilization_s: float = 60.0,
        scrape: Optional[Callable[[str], dict]] = None,
        health_timeout_s: float = 2.0,
    ):
        super().__init__(api, registry)
        self.tracer = tracer
        self.interval_s = interval_s
        self.scale_down_stabilization_s = scale_down_stabilization_s
        self.health_timeout_s = health_timeout_s
        # Injectable scrape (addr -> engine load dict, {} on failure):
        # tests and the in-process bench bypass HTTP; production scrapes
        # each replica's /healthz.
        self.scrape = scrape or self._scrape_http
        self.recorder = EventRecorder(api, self.NAME)
        self.metrics_decisions = registry.counter(
            "kftpu_autoscaler_replicas",
            "Replicas added/removed by autoscale decisions",
            labels=("reason",),
        )
        # (namespace, name) -> monotonic time the signal first sat inside
        # the scale-down band; cleared by any non-quiet scrape.
        self._below_since: Dict[Tuple[str, str], float] = {}

    # ------------- scrape -------------

    def _scrape_http(self, addr: str) -> dict:
        """One replica's engine load snapshot via its /healthz ("load"
        key, ServingEngine.load). {} on any failure — an unreachable
        replica contributes no signal rather than a fake zero."""
        try:
            with urllib.request.urlopen(
                f"http://{addr}/healthz", timeout=self.health_timeout_s
            ) as r:
                body = json.load(r)
        except Exception:  # noqa: BLE001 — scrape failure = no signal
            return {}
        load = body.get("load")
        return load if isinstance(load, dict) else {}

    # ------------- reconcile -------------

    def reconcile(self, namespace: str, name: str) -> Result:
        key = (namespace, name)
        sv = self.api.try_get("Serving", name, namespace)
        if sv is None or sv.metadata.deletion_timestamp is not None:
            self._below_since.pop(key, None)
            return Result()
        a = sv.spec.autoscale
        if a is None:
            self._below_since.pop(key, None)
            return Result()

        lo = max(1, a.min_replicas)
        hi = max(lo, a.max_replicas)
        cur = max(1, sv.spec.replicas)

        with self.tracer.span(
            "autoscale.scrape",
            attrs={"kind": "Serving", "namespace": namespace, "name": name,
                   "endpoints": len(sv.status.endpoints)},
        ) as scrape_span:
            loads = [l for l in (self.scrape(ep)
                                 for ep in sv.status.endpoints) if l]
            wait = max(
                (float(l.get("p95_queue_wait_s",
                             l.get("p50_queue_wait_s", 0.0)))
                 for l in loads), default=0.0)
            queued = sum(int(l.get("queued", 0)) for l in loads)
            scrape_span.attrs["replicas_reporting"] = len(loads)
            scrape_span.attrs["p95_queue_wait_s"] = round(wait, 6)
            scrape_span.attrs["queued"] = queued

        want, reason = cur, ""
        now = time.monotonic()
        if cur < lo:
            want, reason = lo, "min-replicas"
        elif cur > hi:
            want, reason = hi, "max-replicas"
        elif loads and wait > a.target_queue_wait_s:
            # Overload: proportional scale-up, at least one replica, now.
            want = min(hi, max(
                cur + 1,
                int(math.ceil(cur * wait / a.target_queue_wait_s))))
            reason = "queue-wait-above-target"
            self._below_since.pop(key, None)
        elif loads and wait < SCALE_DOWN_BAND * a.target_queue_wait_s \
                and queued == 0:
            # Quiet: start (or continue) the stabilization clock; only a
            # full uninterrupted window earns ONE replica of scale-down.
            since = self._below_since.setdefault(key, now)
            if cur > lo and now - since >= self.scale_down_stabilization_s:
                want, reason = cur - 1, "queue-wait-below-target"
                self._below_since[key] = now   # window restarts per step
        else:
            # In-band (or no signal): neither direction, clock reset.
            self._below_since.pop(key, None)

        if want != cur:
            with self.tracer.span(
                "autoscale.decision",
                attrs={"kind": "Serving", "namespace": namespace,
                       "name": name, "from": cur, "to": want,
                       "reason": reason,
                       "p95_queue_wait_s": round(wait, 6),
                       "queued": queued},
                links=[scrape_span.context],
            ):
                live = self.api.try_get("Serving", name, namespace)
                if live is None:
                    return Result()
                live.spec.replicas = want
                self.api.update(live)
            self.metrics_decisions.inc(abs(want - cur), reason=reason)
            self.recorder.event(
                sv, "Normal", "Scaled",
                f"replicas {cur} -> {want} ({reason}, "
                f"p95_queue_wait={wait:.3f}s target="
                f"{a.target_queue_wait_s}s queued={queued})")
            self.log.info("autoscale decision", kv={
                "serving": f"{namespace}/{name}", "from": cur, "to": want,
                "reason": reason, "p95_queue_wait_s": round(wait, 4)})

        # Keep polling: latency pressure changes without API writes, so
        # the controller re-arms its own scrape timer.
        return Result(requeue_after=self.interval_s)
