"""Notebook controller: Notebook CR -> Pod + Service + VirtualService,
with idle culling.

Mirrors components/notebook-controller/controllers/notebook_controller.go:
- workload + ClusterIP service + VirtualService route
  ``/notebook/<ns>/<name>/`` (:278-435, :378-435)
- container state mirrored into CR conditions (:196-227)
- culling via stop annotation when idle beyond IDLE_TIME
  (pkg/culler/culler.go:138-206) — activity here comes from an injectable
  probe (production: Jupyter /api/status; tests: annotation), instead of
  the reference's hardcoded HTTP poll.

TPU twist: ``spec.tpu_slice`` attaches a single-host slice (e.g. v5e-8) via
node selectors + google.com/tpu resources, replacing the GPU vendor limits
the reference's spawner injects (jupyter-web-app .../utils.py:390-443).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from kubeflow_tpu.controlplane.api.core import (
    Container,
    EnvVar,
    HttpRoute,
    Pod,
    PodSpec,
    Service,
    ServicePort,
    ServiceSpec,
    VirtualService,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    EventRecorder,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.topology import get_slice
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

STOP_ANNOTATION = "kubeflow-resource-stopped"
LAST_ACTIVITY_ANNOTATION = "notebooks.tpu.kubeflow.org/last-activity"
NB_PREFIX_ENV = "NB_PREFIX"
NOTEBOOK_PORT = 8888


class NotebookController(Controller):
    NAME = "notebook"
    WATCH_KINDS = ("Notebook", "Pod")

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        enable_culling: bool = False,
        idle_seconds: float = 1440 * 60,
        culling_check_period: float = 60.0,
        istio_gateway: str = "kubeflow/kubeflow-gateway",
        activity_probe: Optional[Callable[[Pod], Optional[float]]] = None,
    ):
        super().__init__(api, registry)
        self.enable_culling = enable_culling
        self.idle_seconds = idle_seconds
        self.culling_check_period = culling_check_period
        self.istio_gateway = istio_gateway
        self.activity_probe = activity_probe or self._annotation_probe
        self.recorder = EventRecorder(api, self.NAME)
        self.metrics_created = registry.counter(
            "kftpu_notebook_create_total", "Notebooks reconciled into existence"
        )
        self.metrics_culls = registry.counter(
            "kftpu_notebook_cull_total", "Notebooks culled for idleness"
        )

    @staticmethod
    def _annotation_probe(pod: Pod) -> Optional[float]:
        v = pod.metadata.annotations.get(LAST_ACTIVITY_ANNOTATION)
        return float(v) if v else None

    @staticmethod
    def http_activity_probe(port: int = NOTEBOOK_PORT,
                            timeout: float = 5.0):
        """Production probe: poll Jupyter's /api/status on the pod IP and
        parse ``last_activity`` (the reference culler's exact mechanism,
        pkg/culler/culler.go:138-206). Returns a probe fn for the
        ``activity_probe`` constructor arg."""
        import json as _json
        import urllib.request
        from datetime import datetime, timezone

        def probe(pod: Pod) -> Optional[float]:
            if not pod.status.pod_ip:
                return None
            url = f"http://{pod.status.pod_ip}:{port}/api/status"
            try:
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    data = _json.loads(resp.read())
            except (OSError, ValueError):
                return None
            if not isinstance(data, dict):
                return None
            raw = data.get("last_activity")
            if not isinstance(raw, str):        # null / absent / wrong type
                return None
            try:
                # Jupyter emits ISO-8601 UTC, e.g. 2026-07-30T01:00:00.000000Z
                dt = datetime.fromisoformat(raw.replace("Z", "+00:00"))
                return dt.astimezone(timezone.utc).timestamp()
            except ValueError:
                return None

        return probe

    def reconcile(self, namespace: str, name: str) -> Result:
        nb = self.api.try_get("Notebook", name, namespace)
        if nb is None or nb.metadata.deletion_timestamp is not None:
            return Result()

        stopped = STOP_ANNOTATION in nb.metadata.annotations
        pod_name = f"{name}-0"
        live_pod = self.api.try_get("Pod", pod_name, namespace)

        if stopped:
            if live_pod is not None:
                self.api.delete("Pod", pod_name, namespace)
            nb.status.ready_replicas = 0
            nb.status.container_state = "Stopped"
            nb.status.conditions = set_condition(
                nb.status.conditions,
                Condition(type="Ready", status="False", reason="Stopped",
                          message="culled or stopped by user"),
            )
            self._sync_status(nb)
            return Result()

        if live_pod is None:
            restore_dir = ""
            if nb.spec.checkpoint:
                from kubeflow_tpu.controlplane.ckpt_catalog import (
                    resolve_checkpoint,
                )

                entry = resolve_checkpoint(self.api, namespace,
                                           nb.spec.checkpoint)
                if entry is None:
                    # Loud + recoverable: surface the miss as a condition
                    # and retry (the producing job may still be saving its
                    # first step). The event fires only on the TRANSITION
                    # into this state — a waiting notebook requeues every
                    # 5s and must not mint an Event per tick.
                    already = any(
                        c.type == "Ready"
                        and c.reason == "CheckpointNotFound"
                        for c in nb.status.conditions)
                    nb.status.container_state = "Waiting"
                    nb.status.conditions = set_condition(
                        nb.status.conditions,
                        Condition(type="Ready", status="False",
                                  reason="CheckpointNotFound",
                                  message=f"checkpoint {nb.spec.checkpoint!r}"
                                          " has no completed step (or its "
                                          "TpuJob is gone)"),
                    )
                    self._sync_status(nb)
                    if not already:
                        self.recorder.event(
                            nb, "Warning", "CheckpointNotFound",
                            f"no checkpoint named {nb.spec.checkpoint!r}")
                    return Result(requeue_after=5.0)
                restore_dir = entry["dir"]
            self.api.create(self._pod(nb, pod_name, restore_dir=restore_dir))
            self.metrics_created.inc()
            self.recorder.event(nb, "Normal", "Created", f"pod {pod_name}")
            live_pod = self.api.get("Pod", pod_name, namespace)

        create_or_update(self.api, self._service(nb))
        create_or_update(self.api, self._virtual_service(nb))

        # Mirror pod state into CR conditions (reference :196-227).
        phase = live_pod.status.phase
        nb.status.container_state = phase
        nb.status.ready_replicas = 1 if phase == "Running" else 0
        nb.status.conditions = set_condition(
            nb.status.conditions,
            Condition(type="Ready",
                      status="True" if phase == "Running" else "False",
                      reason=phase, message=live_pod.status.message),
        )
        last = self.activity_probe(live_pod)
        if last is not None:
            nb.status.last_activity = last
        self._sync_status(nb)

        # Culling loop (reference culler.go:138-206): requeue each period,
        # stop-annotate when idle beyond the threshold.
        if self.enable_culling and phase == "Running":
            last_activity = nb.status.last_activity or (
                live_pod.metadata.creation_timestamp
            )
            if time.time() - last_activity > self.idle_seconds:
                fresh = self.api.get("Notebook", name, namespace)
                fresh.metadata.annotations[STOP_ANNOTATION] = str(time.time())
                self.api.update(fresh)
                self.metrics_culls.inc()
                self.recorder.event(
                    nb, "Normal", "Culled",
                    f"idle for more than {self.idle_seconds}s",
                )
                return Result()
            return Result(requeue_after=self.culling_check_period)
        return Result()

    def _sync_status(self, nb) -> None:
        live = self.api.try_get("Notebook", nb.metadata.name, nb.metadata.namespace)
        if live is not None and live.status != nb.status:
            live.status = nb.status
            self.api.update_status(live)

    # ------------- emitted objects -------------

    def _owner(self, nb) -> OwnerReference:
        return OwnerReference(kind="Notebook", name=nb.metadata.name,
                              uid=nb.metadata.uid)

    def _pod(self, nb, pod_name: str, restore_dir: str = "") -> Pod:
        ns, name = nb.metadata.namespace, nb.metadata.name
        resources = {"cpu": nb.spec.cpu, "memory": nb.spec.memory}
        node_selector = {}
        if nb.spec.tpu_slice:
            st = get_slice(nb.spec.tpu_slice)
            if st.num_hosts != 1:
                raise ValueError(
                    f"notebook TPU must be single-host, {st.name} has "
                    f"{st.num_hosts} hosts"
                )
            resources[st.resource_name()] = str(st.chips_per_host)
            node_selector = st.node_selectors()
        env = [EnvVar(NB_PREFIX_ENV, f"/notebook/{ns}/{name}")] + list(nb.spec.env)
        annotations = {}
        if restore_dir:
            # Spawn-from-checkpoint: the in-pod kernel restores from here
            # (train.CheckpointService.restore_latest reads the same
            # layout the producing TpuJob wrote).
            env.append(EnvVar("KFTPU_RESTORE_DIR", restore_dir))
            annotations["checkpoint-source.tpu.kubeflow.org/job"] = \
                nb.spec.checkpoint
        return Pod(
            metadata=ObjectMeta(
                name=pod_name, namespace=ns,
                labels={"statefulset": name, "notebook-name": name,
                        **nb.metadata.labels},
                annotations=annotations,
                owner_references=[self._owner(nb)],
            ),
            spec=PodSpec(
                containers=[Container(
                    name=name, image=nb.spec.image, env=env,
                    ports=[NOTEBOOK_PORT], resources=resources,
                    volume_mounts=list(nb.spec.volume_mounts),
                )],
                volumes=list(nb.spec.volumes),
                node_selector=node_selector,
                service_account="default-editor",
            ),
        )

    def _service(self, nb) -> Service:
        name, ns = nb.metadata.name, nb.metadata.namespace
        return Service(
            metadata=ObjectMeta(name=name, namespace=ns,
                                owner_references=[self._owner(nb)]),
            spec=ServiceSpec(
                selector={"statefulset": name},
                ports=[ServicePort(name="http", port=80,
                                   target_port=NOTEBOOK_PORT)],
            ),
        )

    def _virtual_service(self, nb) -> VirtualService:
        name, ns = nb.metadata.name, nb.metadata.namespace
        prefix = f"/notebook/{ns}/{name}/"
        return VirtualService(
            metadata=ObjectMeta(name=f"notebook-{name}", namespace=ns,
                                owner_references=[self._owner(nb)]),
            gateways=[self.istio_gateway],
            hosts=["*"],
            http=[HttpRoute(prefix=prefix, rewrite="/",
                            destination_host=f"{name}.{ns}.svc.cluster.local",
                            destination_port=80)],
        )
