"""Local compute backends: fake and real-process kubelets (SURVEY.md §7.8).

The reference has NO fake backend for compute — multi-node behaviour is
only tested on real GKE clusters (SURVEY.md §4 point 3). This closes that
gap twice over:

- ``FakeKubelet``: plays kubelet+scheduler for unit tests, moving pods
  Pending -> Running and completing/failing them per a script.
- ``ProcessKubelet``: EXECUTES pods as real local subprocesses — worker
  gangs become actual ``train.runner`` processes doing
  ``jax.distributed.initialize`` against the controller-injected env, pod
  deletion kills the process, exit codes become pod phases, and the
  termination-message file round-trips worker metrics. The E2E tier
  (tests/e2e) runs the platform's whole failure loop on it: kill a worker
  mid-run, watch gang restart + checkpoint auto-resume — what the
  reference could only attempt on a live GKE cluster
  (testing/kfctl/kf_is_ready_test.py + Argo workflows).
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

from kubeflow_tpu.controlplane.runtime import (
    ApiError,
    Controller,
    InMemoryApiServer,
    Result,
)
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

log = get_logger("podrunner")


class FakeKubelet(Controller):
    NAME = "fake-kubelet"
    WATCH_KINDS = ("Pod",)

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        # pod name predicate -> terminal phase ("Succeeded"/"Failed");
        # pods not matched stay Running.
        outcome: Optional[Callable[[str], Optional[str]]] = None,
        # called with the Pod when it goes terminal; returns the container
        # termination message (the terminationMessagePath channel a real
        # kubelet surfaces — lets tests "run" a workload deterministically,
        # e.g. compute a loss from the pod's KFTPU_HPARAMS env).
        termination: Optional[Callable[[Any], str]] = None,
        auto_run: bool = True,
        # Cold-start model (ISSUE 11): a Pending pod stays Pending for
        # this many kubelet observations before Running — the gang
        # spin-up window (jax.distributed.initialize, compile, restore)
        # a restart pays. Pods labeled warm-start: "true" (created into
        # an elastic gang mid-resize, whose world stays initialized —
        # the VirtualFlow contract) skip it. 0 = immediate, the
        # pre-elastic behaviour everywhere.
        warmup_ticks: int = 0,
    ):
        super().__init__(api, registry)
        self.outcome = outcome
        self.termination = termination
        self.auto_run = auto_run
        self.warmup_ticks = warmup_ticks
        self._warm_seen: Dict[str, int] = {}   # pod uid -> observations

    def map_to_primary(self, obj):
        return (obj.metadata.namespace, obj.metadata.name)

    def tick(self) -> None:
        """Simulate a kubelet status-sync pass: re-reconcile every pod (the
        outcome script may have changed). Tests call this, then drain the
        manager to propagate the resulting watch events. Per-pod API errors
        (conflicts/transients under chaos injection) are swallowed — a real
        kubelet's status sync just retries next pass."""
        try:
            # Zero-copy read: only names are taken here; reconcile()
            # re-reads each pod as a private copy before mutating status.
            pods = self.reader.list("Pod", copy=False)
        except ApiError:
            return  # status sync skipped this pass; next tick retries
        if self._warm_seen:
            # Prune warmup counters of pods deleted mid-warmup (torn
            # down while still Pending) — long oscillation soaks would
            # otherwise accumulate one stale uid per interrupted
            # cold-start.
            live = {p.metadata.uid for p in pods}
            self._warm_seen = {u: n for u, n in self._warm_seen.items()
                               if u in live}
        for pod in pods:
            try:
                self.reconcile(pod.metadata.namespace, pod.metadata.name)
            except ApiError:
                continue

    def reconcile(self, namespace: str, name: str) -> Result:
        # Zero-copy peek first: most passes observe a pod that needs no
        # transition (Running with no outcome, terminal). Only an actual
        # phase change pays the private-copy read before mutating.
        pod = self.api.try_get("Pod", name, namespace, copy=False)
        if pod is None:
            return Result()
        if pod.status.phase == "Pending" and self.auto_run:
            if self.warmup_ticks > 0 and \
                    pod.metadata.labels.get("warm-start") != "true":
                uid = pod.metadata.uid
                seen = self._warm_seen.get(uid, 0) + 1
                self._warm_seen[uid] = seen
                if seen <= self.warmup_ticks:
                    return Result()     # still cold-initializing
                self._warm_seen.pop(uid, None)
            pod = self.api.try_get("Pod", name, namespace)
            if pod is None or pod.status.phase != "Pending":
                return Result()
            pod.status.phase = "Running"
            pod.status.pod_ip = f"10.0.0.{abs(hash(name)) % 250 + 1}"
            pod.status.node_name = f"node-{abs(hash(name)) % 16}"
            self.api.update_status(pod)
            return Result()
        if pod.status.phase == "Running" and self.outcome is not None:
            term = self.outcome(name)
            if term in ("Succeeded", "Failed"):
                pod = self.api.try_get("Pod", name, namespace)
                if pod is None or pod.status.phase != "Running":
                    return Result()
                pod.status.phase = term
                if self.termination is not None:
                    pod.status.termination_message = self.termination(pod)
                self.api.update_status(pod)
        return Result()


class ProcessKubelet(Controller):
    """Kubelet that runs pods as local subprocesses.

    - Pending pod -> spawn ``containers[0].command`` (a leading "python"
      maps to sys.executable) with the pod's env on top of the parent env
      plus ``base_env`` and per-pod ``env_overrides(pod)``; phase Running.
    - ``sync()`` harvests exits: rc 0 -> Succeeded, else Failed; the
      termination-message file (KFTPU_TERMINATION_LOG, injected per pod)
      lands in pod.status.termination_message exactly as a kubelet lifts
      terminationMessagePath.
    - Pod deleted -> process killed (gang teardown on restart).
    - stdout/stderr stream into ``log_dir/<pod>.log`` for debugging.
    """

    NAME = "process-kubelet"
    WATCH_KINDS = ("Pod",)
    LOG_PATH_ANNOTATION = "tpu.kubeflow.org/log-path"

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        base_env: Optional[Dict[str, str]] = None,
        env_overrides: Optional[Callable[[Any], Dict[str, str]]] = None,
        log_dir: Optional[str] = None,
    ):
        super().__init__(api, registry)
        self.base_env = dict(base_env or {})
        self.env_overrides = env_overrides
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="kftpu-pods-")
        os.makedirs(self.log_dir, exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}   # "ns/name" -> proc
        self._uids: Dict[str, str] = {}                 # pod uid at spawn
        self._termfiles: Dict[str, str] = {}
        self._logfiles: Dict[str, Any] = {}

    def map_to_primary(self, obj):
        return (obj.metadata.namespace, obj.metadata.name)

    # ------------- lifecycle -------------

    def _spawn(self, pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        c = pod.spec.containers[0]
        cmd = list(c.command) + list(c.args)
        if not cmd:
            cmd = ["python", "-m", "kubeflow_tpu.train.runner"]
        if cmd[0] == "python":
            cmd[0] = sys.executable
        # Namespace-qualified files: same-named pods in different namespaces
        # must not share termination/log channels.
        stem = f"{pod.metadata.namespace}__{pod.metadata.name}"
        term = os.path.join(self.log_dir, f"{stem}.term")
        logpath = self.log_path(pod.metadata.name, pod.metadata.namespace)
        env = dict(os.environ)
        env.update(self.base_env)
        env.update({e.name: e.value for e in c.env})
        env["KFTPU_TERMINATION_LOG"] = term
        if self.env_overrides is not None:
            env.update(self.env_overrides(pod))
        logf = open(logpath, "ab")
        self._procs[key] = subprocess.Popen(
            cmd, env=env, stdout=logf, stderr=subprocess.STDOUT,
        )
        self._uids[key] = pod.metadata.uid
        self._termfiles[key] = term
        self._logfiles[key] = logf
        log.info("spawned pod process",
                 kv={"pod": key, "pid": self._procs[key].pid})

    def _kill(self, key: str) -> None:
        proc = self._procs.pop(key, None)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        f = self._logfiles.pop(key, None)
        if f is not None:
            f.close()
        self._termfiles.pop(key, None)
        self._uids.pop(key, None)

    def kill_pod(self, name: str, namespace: str) -> bool:
        """Test hook: hard-kill a worker process (SIGKILL), simulating a
        node/worker crash. The next sync() surfaces the failure."""
        proc = self._procs.get(f"{namespace}/{name}")
        if proc is None or proc.poll() is not None:
            return False
        proc.kill()
        return True

    def reconcile(self, namespace: str, name: str) -> Result:
        key = f"{namespace}/{name}"
        pod = self.api.try_get("Pod", name, namespace)
        if pod is None or pod.metadata.deletion_timestamp is not None:
            self._kill(key)
            return Result()
        if (key in self._procs and pod.metadata.uid
                and self._uids.get(key) not in ("", pod.metadata.uid)):
            # Same-named pod recreated before we saw the deletion (gang
            # restart with elapsed backoff): the tracked process belongs to
            # the OLD generation — kill it so the new pod can spawn.
            self._kill(key)
        if pod.status.phase == "Pending" and key not in self._procs:
            # Annotate the log path BEFORE spawning: an update conflict
            # then simply requeues with nothing started, whereas failing
            # between spawn and the Running write would strand a live
            # process behind a forever-Pending pod.
            pod.metadata.annotations[self.LOG_PATH_ANNOTATION] = \
                self.log_path(name, namespace)
            pod = self.api.update(pod)
            self._spawn(pod)
            pod.status.phase = "Running"
            pod.status.pod_ip = "127.0.0.1"
            pod.status.node_name = "local"
            self.api.update_status(pod)
        return Result()

    def log_path(self, name: str, namespace: str) -> str:
        return os.path.join(self.log_dir, f"{namespace}__{name}.log")

    def sync(self) -> int:
        """Harvest exited processes into pod phases. Returns the number of
        pods transitioned (callers loop: sync + drain manager)."""
        moved = 0
        for key, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            ns, name = key.split("/", 1)
            pod = self.api.try_get("Pod", name, ns)
            self._logfiles[key].flush()
            if pod is not None and pod.status.phase == "Running":
                pod.status.phase = "Succeeded" if rc == 0 else "Failed"
                pod.status.message = f"exit code {rc}"
                termfile = self._termfiles.get(key, "")
                if termfile and os.path.exists(termfile):
                    with open(termfile) as f:
                        pod.status.termination_message = f.read()
                self.api.update_status(pod)
                moved += 1
            self._kill(key)
        return moved

    def shutdown(self) -> None:
        for key in list(self._procs):
            self._kill(key)
