"""FakeKubelet: the compute-side test double (SURVEY.md §7.8).

The reference has NO fake backend for compute — multi-node behaviour is
only tested on real GKE clusters (SURVEY.md §4 point 3). This closes that
gap: a controller that plays kubelet+scheduler for tests and local dev,
moving pods Pending -> Running (honouring TPU capacity per node selector)
and optionally completing/failing them per a script.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from kubeflow_tpu.controlplane.runtime import (
    Controller,
    InMemoryApiServer,
    Result,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry


class FakeKubelet(Controller):
    NAME = "fake-kubelet"
    WATCH_KINDS = ("Pod",)

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        # pod name predicate -> terminal phase ("Succeeded"/"Failed");
        # pods not matched stay Running.
        outcome: Optional[Callable[[str], Optional[str]]] = None,
        # called with the Pod when it goes terminal; returns the container
        # termination message (the terminationMessagePath channel a real
        # kubelet surfaces — lets tests "run" a workload deterministically,
        # e.g. compute a loss from the pod's KFTPU_HPARAMS env).
        termination: Optional[Callable[[Any], str]] = None,
        auto_run: bool = True,
    ):
        super().__init__(api, registry)
        self.outcome = outcome
        self.termination = termination
        self.auto_run = auto_run

    def map_to_primary(self, obj):
        return (obj.metadata.namespace, obj.metadata.name)

    def tick(self) -> None:
        """Simulate a kubelet status-sync pass: re-reconcile every pod (the
        outcome script may have changed). Tests call this, then drain the
        manager to propagate the resulting watch events."""
        for pod in self.api.list("Pod"):
            self.reconcile(pod.metadata.namespace, pod.metadata.name)

    def reconcile(self, namespace: str, name: str) -> Result:
        pod = self.api.try_get("Pod", name, namespace)
        if pod is None:
            return Result()
        if pod.status.phase == "Pending" and self.auto_run:
            pod.status.phase = "Running"
            pod.status.pod_ip = f"10.0.0.{abs(hash(name)) % 250 + 1}"
            pod.status.node_name = f"node-{abs(hash(name)) % 16}"
            self.api.update_status(pod)
            return Result()
        if pod.status.phase == "Running" and self.outcome is not None:
            term = self.outcome(name)
            if term in ("Succeeded", "Failed"):
                pod.status.phase = term
                if self.termination is not None:
                    pod.status.termination_message = self.termination(pod)
                self.api.update_status(pod)
        return Result()
