"""Tensorboard controller: Tensorboard CR -> Pod + Service + VirtualService
at /tensorboard/<ns>/<name>/.

Mirrors components/tensorboard-controller/controllers/
tensorboard_controller.go:54-277. TPU twist (SURVEY.md §5 Tracing): the CR
carries ``trace_dir`` so a board can serve JAX profiler traces captured by
TpuJob workers — the tracing surface the reference lacks entirely.
"""

from __future__ import annotations

from kubeflow_tpu.controlplane.api.core import (
    Container,
    EnvVar,
    HttpRoute,
    Pod,
    PodSpec,
    Service,
    ServicePort,
    ServiceSpec,
    VirtualService,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    InMemoryApiServer,
    Result,
    create_or_update,
)

TB_PORT = 6006


class TensorboardController(Controller):
    NAME = "tensorboard"
    WATCH_KINDS = ("Tensorboard", "Pod")

    def __init__(self, api: InMemoryApiServer, registry=None, *,
                 istio_gateway: str = "kubeflow/kubeflow-gateway"):
        from kubeflow_tpu.utils.monitoring import global_registry

        super().__init__(api, registry or global_registry)
        self.istio_gateway = istio_gateway

    def reconcile(self, namespace: str, name: str) -> Result:
        tb = self.api.try_get("Tensorboard", name, namespace)
        if tb is None or tb.metadata.deletion_timestamp is not None:
            return Result()
        owner = OwnerReference(kind="Tensorboard", name=name, uid=tb.metadata.uid)

        logdir = tb.spec.logspath
        args = [f"--logdir={logdir}", f"--path_prefix=/tensorboard/{namespace}/{name}/"]
        if tb.spec.trace_dir:
            args.append(f"--load_fast=false")
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{name}-tb", namespace=namespace,
                labels={"app": "tensorboard", "tb-name": name},
                owner_references=[owner],
            ),
            spec=PodSpec(containers=[Container(
                name="tensorboard",
                image="kubeflow-tpu/tensorboard:latest",
                command=["tensorboard"],
                args=args,
                env=[EnvVar("KFTPU_TRACE_DIR", tb.spec.trace_dir)],
                ports=[TB_PORT],
                resources={"cpu": "1", "memory": "2Gi"},
            )]),
        )
        create_or_update(self.api, pod, copy_fields=lambda a, b: False)
        create_or_update(self.api, Service(
            metadata=ObjectMeta(name=f"{name}-tb", namespace=namespace,
                                owner_references=[owner]),
            spec=ServiceSpec(selector={"tb-name": name},
                             ports=[ServicePort(name="http", port=80,
                                                target_port=TB_PORT)]),
        ))
        create_or_update(self.api, VirtualService(
            metadata=ObjectMeta(name=f"tensorboard-{name}", namespace=namespace,
                                owner_references=[owner]),
            gateways=[self.istio_gateway],
            hosts=["*"],
            http=[HttpRoute(prefix=f"/tensorboard/{namespace}/{name}/",
                            rewrite="/",
                            destination_host=f"{name}-tb.{namespace}.svc.cluster.local",
                            destination_port=80)],
        ))

        live_pod = self.api.try_get("Pod", f"{name}-tb", namespace)
        ready = live_pod is not None and live_pod.status.phase == "Running"
        if tb.status.ready != ready:
            tb.status.ready = ready
            tb.status.conditions = set_condition(
                tb.status.conditions,
                Condition(type="Ready", status="True" if ready else "False",
                          reason=live_pod.status.phase if live_pod else "NoPod"),
            )
            self.api.update_status(tb)
        return Result()
