"""Profile controller: per-user namespace + RBAC + quota.

Mirrors components/profile-controller/controllers/profile_controller.go:100-310:
namespace with owner annotation + istio-injection label (:121-186),
ServiceAccounts default-editor/default-viewer bound to kubeflow-edit/
kubeflow-view (:196-212), owner admin RoleBinding (:216-239), ResourceQuota
(:240-256), plus a modern AuthorizationPolicy instead of the deprecated
ServiceRole pair (:188-194; SURVEY.md §7 hardest-parts item 4).

TPU twist: Profile.spec.tpu_chip_quota emits a google.com/tpu ResourceQuota
that the TpuJob controller's gang admission enforces.
"""

from __future__ import annotations

import dataclasses

from kubeflow_tpu.controlplane.api.core import (
    AuthorizationPolicy,
    Namespace,
    ResourceQuota,
    RoleBinding,
    RoleRef,
    ServiceAccount,
    Subject,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

OWNER_ANNOTATION = "owner"
ADMIN_CLUSTER_ROLE = "kubeflow-admin"
EDIT_CLUSTER_ROLE = "kubeflow-edit"
VIEW_CLUSTER_ROLE = "kubeflow-view"
PLUGIN_FINALIZER = "profile-plugins.tpu.kubeflow.org"
WI_ANNOTATION = "iam.gke.io/gcp-service-account"


class WorkloadIdentityPlugin:
    """Workload-identity plugin (plugin_workload_identity.go:44-166): binds
    the namespace's default-editor KSA to a GCP service account — the KSA
    annotation is the real mechanism; the IAM policy mutation (a cloud API
    call) goes through the injectable ``iam`` store so tests (and clusters
    without GCP) run against a fake while the seam stays production-shaped.
    """

    KIND = "WorkloadIdentity"

    def __init__(self, iam=None):
        # gsa -> set of "serviceAccount:<ns>/<ksa>" members; a real impl
        # replaces this with google.golang.org/api/iam-style policy calls.
        self.iam = iam if iam is not None else {}

    def apply(self, api, profile, params) -> None:
        gsa = params.get("gcpServiceAccount", "")
        if not gsa:
            raise ValueError("WorkloadIdentity needs params.gcpServiceAccount")
        ns = profile.metadata.name
        sa = api.get("ServiceAccount", "default-editor", ns)
        if sa.metadata.annotations.get(WI_ANNOTATION) != gsa:
            sa.metadata.annotations[WI_ANNOTATION] = gsa
            api.update(sa)
        self.iam.setdefault(gsa, set()).add(f"serviceAccount:{ns}/default-editor")

    def revoke(self, api, profile, params) -> None:
        gsa = params.get("gcpServiceAccount", "")
        ns = profile.metadata.name
        sa = api.try_get("ServiceAccount", "default-editor", ns)
        if sa is not None and WI_ANNOTATION in sa.metadata.annotations:
            del sa.metadata.annotations[WI_ANNOTATION]
            api.update(sa)
        if gsa in self.iam:
            self.iam[gsa].discard(f"serviceAccount:{ns}/default-editor")


class AwsIamForServiceAccountPlugin:
    """AWS IRSA plugin (the reference's second cloud-IAM impl,
    plugin_iam.go:32-283): annotates the namespace's default-editor KSA
    with the IAM role ARN (``eks.amazonaws.com/role-arn`` — what the EKS
    pod identity webhook consumes) and adds the service account to the
    role's OIDC trust policy. The trust-policy mutation (an AWS STS/IAM
    API call in the reference, UpdateAssumeRolePolicy) goes through the
    injectable ``iam`` store, same seam shape as WorkloadIdentityPlugin —
    proving the seam fits more than one cloud.
    """

    KIND = "AwsIamForServiceAccount"
    ANNOTATION = "eks.amazonaws.com/role-arn"

    def __init__(self, iam=None):
        # role_arn -> set of "system:serviceaccount:<ns>:<ksa>" trust
        # principals; a real impl issues UpdateAssumeRolePolicy calls.
        self.iam = iam if iam is not None else {}

    @staticmethod
    def _principal(ns: str) -> str:
        return f"system:serviceaccount:{ns}:default-editor"

    def apply(self, api, profile, params) -> None:
        role = params.get("awsIamRole", "")
        if not role:
            raise ValueError(
                "AwsIamForServiceAccount needs params.awsIamRole")
        ns = profile.metadata.name
        sa = api.get("ServiceAccount", "default-editor", ns)
        if sa.metadata.annotations.get(self.ANNOTATION) != role:
            sa.metadata.annotations[self.ANNOTATION] = role
            api.update(sa)
        self.iam.setdefault(role, set()).add(self._principal(ns))

    def revoke(self, api, profile, params) -> None:
        role = params.get("awsIamRole", "")
        ns = profile.metadata.name
        sa = api.try_get("ServiceAccount", "default-editor", ns)
        if sa is not None and self.ANNOTATION in sa.metadata.annotations:
            del sa.metadata.annotations[self.ANNOTATION]
            api.update(sa)
        if role in self.iam:
            self.iam[role].discard(self._principal(ns))


class ProfileController(Controller):
    NAME = "profile"
    WATCH_KINDS = ("Profile", "Namespace", "RoleBinding")

    def __init__(self, api: InMemoryApiServer,
                 registry: MetricsRegistry = global_registry,
                 *, user_id_header: str = "x-goog-authenticated-user-email",
                 plugins=None):
        super().__init__(api, registry)
        self.user_id_header = user_id_header
        if plugins is not None:
            self.plugins = plugins
        else:
            defaults = (WorkloadIdentityPlugin(),
                        AwsIamForServiceAccountPlugin())
            self.plugins = {p.KIND: p for p in defaults}

    def map_to_primary(self, obj):
        # Namespaces/RoleBindings created for a profile carry its name.
        if obj.kind == "Namespace":
            return ("", obj.metadata.name)
        return super().map_to_primary(obj) or (
            ("", obj.metadata.namespace) if obj.kind == "RoleBinding" else None
        )

    def reconcile(self, namespace: str, name: str) -> Result:
        profile = self.api.try_get("Profile", name)
        if profile is None:
            return Result()
        if profile.metadata.deletion_timestamp is not None:
            # Finalizer path (reference profile_controller.go finalizer
            # handling): revoke whatever is RECORDED as applied (not the
            # spec — the spec may have been edited after grants were made).
            if PLUGIN_FINALIZER in profile.metadata.finalizers:
                for p in profile.status.applied_plugins or profile.spec.plugins:
                    impl = self.plugins.get(p.kind)
                    if impl is not None:
                        impl.revoke(self.api, profile, p.params)
                profile.metadata.finalizers.remove(PLUGIN_FINALIZER)
                self.api.update(profile)
            return Result()
        # Tenant-tree validation (ISSUE 13), top-down: spec
        # contradictions (bad weight, self/cyclic parent, child quota
        # exceeding the parent's) are permanent failures; an unknown
        # parent parks and retries (apply ordering — the child may
        # simply have landed first); children summing past this
        # profile's quota is over-commit: allowed, flagged.
        tenant_blocked = self._tenant_blocked(profile)
        if tenant_blocked is not None:
            reason, msg, requeue = tenant_blocked
            if requeue is None:
                # Permanent spec error: write only on real change — an
                # unconditional write would emit MODIFIED every
                # reconcile and livelock the watch loop.
                prev_phase = profile.status.phase
                prev = [dataclasses.replace(c)
                        for c in profile.status.conditions]
                profile.status.phase = "Failed"
                profile.status.conditions = set_condition(
                    profile.status.conditions,
                    Condition(type="Ready", status="False",
                              reason=reason, message=msg),
                )
                if any(c.type == "TenantTree"
                       for c in profile.status.conditions):
                    # A leftover transient flag (UnknownParent from an
                    # earlier spec) must not outlive the spec that
                    # caused it: point it at the ACTUAL error.
                    profile.status.conditions = set_condition(
                        profile.status.conditions,
                        Condition(type="TenantTree", status="False",
                                  reason=reason, message=msg),
                    )
                if prev_phase != "Failed" \
                        or profile.status.conditions != prev:
                    self.api.update_status(profile)
                return Result()
            prev = [dataclasses.replace(c)
                    for c in profile.status.conditions]
            profile.status.conditions = set_condition(
                profile.status.conditions,
                Condition(type="TenantTree", status="False",
                          reason=reason, message=msg),
            )
            if profile.status.conditions != prev:
                self.api.update_status(profile)
            return Result(requeue_after=requeue)
        if any(c.type == "TenantTree" and c.status == "False"
               for c in profile.status.conditions):
            # The parent arrived (or the spec was fixed): clear the flag.
            profile.status.conditions = set_condition(
                profile.status.conditions,
                Condition(type="TenantTree", status="True",
                          reason="Resolved",
                          message=f"parent {profile.spec.parent or '-'} "
                                  "resolved"),
            )
            self.api.update_status(profile)
        self._refresh_overcommit(profile)
        if profile.spec.parent:
            parent_prof = self.api.try_get("Profile", profile.spec.parent)
            if parent_prof is not None:
                self._refresh_overcommit(parent_prof)
        owner = OwnerReference(kind="Profile", name=name,
                               uid=profile.metadata.uid)

        if profile.spec.plugins and \
                PLUGIN_FINALIZER not in profile.metadata.finalizers:
            # Guard teardown BEFORE applying anything cloud-side.
            profile.metadata.finalizers.append(PLUGIN_FINALIZER)
            profile = self.api.update(profile)

        ns = Namespace(
            metadata=ObjectMeta(
                name=name,
                annotations={OWNER_ANNOTATION: profile.spec.owner},
                labels={"istio-injection": "enabled",
                        "app.kubernetes.io/part-of": "kubeflow-tpu-profile"},
                owner_references=[owner],
            ),
        )
        create_or_update(self.api, ns, copy_fields=self._ns_copy)

        for sa_name in ("default-editor", "default-viewer"):
            create_or_update(self.api, ServiceAccount(
                metadata=ObjectMeta(name=sa_name, namespace=name,
                                    owner_references=[owner]),
            ))
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="default-editor", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="ServiceAccount", name="default-editor")],
            role_ref=RoleRef(name=EDIT_CLUSTER_ROLE),
        ))
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="default-viewer", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="ServiceAccount", name="default-viewer")],
            role_ref=RoleRef(name=VIEW_CLUSTER_ROLE),
        ))
        # Owner becomes namespace admin (reference :216-239).
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="namespaceAdmin", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="User", name=profile.spec.owner)],
            role_ref=RoleRef(name=ADMIN_CLUSTER_ROLE),
        ))
        # Istio-level access for the owner.
        create_or_update(self.api, AuthorizationPolicy(
            metadata=ObjectMeta(name=f"ns-owner-access-istio",
                                namespace=name, owner_references=[owner]),
            principals=[profile.spec.owner],
            user_id_header=self.user_id_header,
        ))

        hard = dict(profile.spec.resource_quota)
        if profile.spec.tpu_chip_quota > 0:
            hard["google.com/tpu"] = str(profile.spec.tpu_chip_quota)
        if hard:
            create_or_update(self.api, ResourceQuota(
                metadata=ObjectMeta(name="kf-resource-quota", namespace=name,
                                    owner_references=[owner]),
                hard=hard,
            ), copy_fields=self._quota_copy)
        elif self.api.try_get("ResourceQuota", "kf-resource-quota",
                              name) is not None:
            # Quota was cleared from the spec: a stale kf-resource-quota must
            # not keep gating the namespace's TpuJobs.
            self.api.delete("ResourceQuota", "kf-resource-quota", name)

        # Revoke grants whose spec entry vanished or changed (diff against
        # the applied ledger, or an edited gcpServiceAccount leaks the old
        # binding forever).
        desired = {(p.kind, tuple(sorted(p.params.items())))
                   for p in profile.spec.plugins}
        still_applied = []
        for p in profile.status.applied_plugins:
            key = (p.kind, tuple(sorted(p.params.items())))
            if key in desired:
                still_applied.append(p)
                continue
            impl = self.plugins.get(p.kind)
            if impl is not None:
                impl.revoke(self.api, profile, p.params)
        applied_changed = still_applied != profile.status.applied_plugins
        profile.status.applied_plugins = still_applied

        for p in profile.spec.plugins:
            impl = self.plugins.get(p.kind)
            try:
                if impl is None:
                    raise ValueError(f"no plugin {p.kind!r} registered")
                impl.apply(self.api, profile, p.params)
            except ValueError as e:
                # Config errors are permanent: surface Failed instead of
                # hot-requeueing forever with no visible signal.
                if profile.status.phase != "Failed" or applied_changed:
                    profile.status.phase = "Failed"
                    profile.status.conditions = set_condition(
                        profile.status.conditions,
                        Condition(type="Ready", status="False",
                                  reason="PluginError", message=str(e)),
                    )
                    self.api.update_status(profile)
                return Result()
            if all((q.kind, tuple(sorted(q.params.items())))
                   != (p.kind, tuple(sorted(p.params.items())))
                   for q in profile.status.applied_plugins):
                profile.status.applied_plugins.append(p)
                applied_changed = True

        if profile.status.phase != "Ready" or applied_changed:
            profile.status.phase = "Ready"
            profile.status.conditions = set_condition(
                profile.status.conditions,
                Condition(type="Ready", status="True", reason="Reconciled",
                          message=f"namespace {name} provisioned"),
            )
            self.api.update_status(profile)
        return Result()

    # ------------- tenant tree (ISSUE 13) -------------

    def _tenant_blocked(self, profile):
        """Validate this profile's place in the tenant tree. Returns
        None when valid, ``(reason, message, None)`` for a permanent
        spec error (phase Failed) or ``(reason, message, requeue_s)``
        for a transient block (unknown parent — apply ordering)."""
        name = profile.metadata.name
        if profile.spec.weight <= 0:
            return ("InvalidTenantSpec",
                    f"spec.weight must be > 0, got {profile.spec.weight}",
                    None)
        if not profile.spec.parent:
            return None
        if profile.spec.parent == name:
            return ("InvalidTenantSpec",
                    "spec.parent must not name the profile itself", None)
        # Walk to the root: a missing link parks (the parent may apply
        # later); a revisit is a cycle — permanent.
        seen = {name}
        cur = profile.spec.parent
        while cur:
            if cur in seen:
                return ("InvalidTenantSpec",
                        f"tenant parent cycle through {cur!r}", None)
            seen.add(cur)
            node = self.api.try_get("Profile", cur)
            if node is None:
                return ("UnknownParent",
                        f"parent Profile {cur!r} does not exist (yet)",
                        30.0)
            cur = node.spec.parent
        parent = self.api.get("Profile", profile.spec.parent)
        if parent.spec.tpu_chip_quota > 0 and \
                profile.spec.tpu_chip_quota > parent.spec.tpu_chip_quota:
            return ("InvalidTenantSpec",
                    f"tpu_chip_quota {profile.spec.tpu_chip_quota} exceeds "
                    f"parent {profile.spec.parent!r} quota "
                    f"{parent.spec.tpu_chip_quota} — a child can never "
                    "out-quota its subtree's share", None)
        return None

    def _refresh_overcommit(self, profile) -> None:
        """Flag (never forbid) over-commit: this profile's children
        declaring more chips than its own quota covers. Written only on
        change — the condition flips both ways as children come and go."""
        quota = profile.spec.tpu_chip_quota
        children = [p for p in self.reader.list("Profile", copy=False)
                    if p.spec.parent == profile.metadata.name]
        child_sum = sum(c.spec.tpu_chip_quota for c in children)
        over = quota > 0 and bool(children) and child_sum > quota
        have = next((c for c in profile.status.conditions
                     if c.type == "QuotaOvercommitted"), None)
        if not over and have is None:
            return
        if have is not None and (have.status == "True") == over:
            return
        profile.status.conditions = set_condition(
            profile.status.conditions,
            Condition(
                type="QuotaOvercommitted",
                status="True" if over else "False",
                reason="ChildQuotaSum",
                message=(f"children declare {child_sum} chips against a "
                         f"quota of {quota}" if over else
                         f"children within quota ({child_sum}/{quota})"),
            ),
        )
        self.api.update_status(profile)

    @staticmethod
    def _ns_copy(live: Namespace, want: Namespace) -> bool:
        changed = False
        for field in ("labels", "annotations"):
            want_map = getattr(want.metadata, field)
            live_map = getattr(live.metadata, field)
            merged = {**live_map, **want_map}
            if merged != live_map:
                setattr(live.metadata, field, merged)
                changed = True
        return changed

    @staticmethod
    def _quota_copy(live: ResourceQuota, want: ResourceQuota) -> bool:
        if live.hard != want.hard:
            live.hard = want.hard
            return True
        return False
