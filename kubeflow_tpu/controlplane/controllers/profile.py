"""Profile controller: per-user namespace + RBAC + quota.

Mirrors components/profile-controller/controllers/profile_controller.go:100-310:
namespace with owner annotation + istio-injection label (:121-186),
ServiceAccounts default-editor/default-viewer bound to kubeflow-edit/
kubeflow-view (:196-212), owner admin RoleBinding (:216-239), ResourceQuota
(:240-256), plus a modern AuthorizationPolicy instead of the deprecated
ServiceRole pair (:188-194; SURVEY.md §7 hardest-parts item 4).

TPU twist: Profile.spec.tpu_chip_quota emits a google.com/tpu ResourceQuota
that the TpuJob controller's gang admission enforces.
"""

from __future__ import annotations

from kubeflow_tpu.controlplane.api.core import (
    AuthorizationPolicy,
    Namespace,
    ResourceQuota,
    RoleBinding,
    RoleRef,
    ServiceAccount,
    Subject,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    InMemoryApiServer,
    Result,
    create_or_update,
)
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

OWNER_ANNOTATION = "owner"
ADMIN_CLUSTER_ROLE = "kubeflow-admin"
EDIT_CLUSTER_ROLE = "kubeflow-edit"
VIEW_CLUSTER_ROLE = "kubeflow-view"


class ProfileController(Controller):
    NAME = "profile"
    WATCH_KINDS = ("Profile", "Namespace", "RoleBinding")

    def __init__(self, api: InMemoryApiServer,
                 registry: MetricsRegistry = global_registry,
                 *, user_id_header: str = "x-goog-authenticated-user-email"):
        super().__init__(api, registry)
        self.user_id_header = user_id_header

    def map_to_primary(self, obj):
        # Namespaces/RoleBindings created for a profile carry its name.
        if obj.kind == "Namespace":
            return ("", obj.metadata.name)
        return super().map_to_primary(obj) or (
            ("", obj.metadata.namespace) if obj.kind == "RoleBinding" else None
        )

    def reconcile(self, namespace: str, name: str) -> Result:
        profile = self.api.try_get("Profile", name)
        if profile is None or profile.metadata.deletion_timestamp is not None:
            return Result()
        owner = OwnerReference(kind="Profile", name=name,
                               uid=profile.metadata.uid)

        ns = Namespace(
            metadata=ObjectMeta(
                name=name,
                annotations={OWNER_ANNOTATION: profile.spec.owner},
                labels={"istio-injection": "enabled",
                        "app.kubernetes.io/part-of": "kubeflow-tpu-profile"},
                owner_references=[owner],
            ),
        )
        create_or_update(self.api, ns, copy_fields=self._ns_copy)

        for sa_name in ("default-editor", "default-viewer"):
            create_or_update(self.api, ServiceAccount(
                metadata=ObjectMeta(name=sa_name, namespace=name,
                                    owner_references=[owner]),
            ))
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="default-editor", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="ServiceAccount", name="default-editor")],
            role_ref=RoleRef(name=EDIT_CLUSTER_ROLE),
        ))
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="default-viewer", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="ServiceAccount", name="default-viewer")],
            role_ref=RoleRef(name=VIEW_CLUSTER_ROLE),
        ))
        # Owner becomes namespace admin (reference :216-239).
        create_or_update(self.api, RoleBinding(
            metadata=ObjectMeta(name="namespaceAdmin", namespace=name,
                                owner_references=[owner]),
            subjects=[Subject(kind="User", name=profile.spec.owner)],
            role_ref=RoleRef(name=ADMIN_CLUSTER_ROLE),
        ))
        # Istio-level access for the owner.
        create_or_update(self.api, AuthorizationPolicy(
            metadata=ObjectMeta(name=f"ns-owner-access-istio",
                                namespace=name, owner_references=[owner]),
            principals=[profile.spec.owner],
            user_id_header=self.user_id_header,
        ))

        hard = dict(profile.spec.resource_quota)
        if profile.spec.tpu_chip_quota > 0:
            hard["google.com/tpu"] = str(profile.spec.tpu_chip_quota)
        if hard:
            create_or_update(self.api, ResourceQuota(
                metadata=ObjectMeta(name="kf-resource-quota", namespace=name,
                                    owner_references=[owner]),
                hard=hard,
            ), copy_fields=self._quota_copy)
        elif self.api.try_get("ResourceQuota", "kf-resource-quota",
                              name) is not None:
            # Quota was cleared from the spec: a stale kf-resource-quota must
            # not keep gating the namespace's TpuJobs.
            self.api.delete("ResourceQuota", "kf-resource-quota", name)

        if profile.status.phase != "Ready":
            profile.status.phase = "Ready"
            profile.status.conditions = set_condition(
                profile.status.conditions,
                Condition(type="Ready", status="True", reason="Reconciled",
                          message=f"namespace {name} provisioned"),
            )
            self.api.update_status(profile)
        return Result()

    @staticmethod
    def _ns_copy(live: Namespace, want: Namespace) -> bool:
        changed = False
        for field in ("labels", "annotations"):
            want_map = getattr(want.metadata, field)
            live_map = getattr(live.metadata, field)
            merged = {**live_map, **want_map}
            if merged != live_map:
                setattr(live.metadata, field, merged)
                changed = True
        return changed

    @staticmethod
    def _quota_copy(live: ResourceQuota, want: ResourceQuota) -> bool:
        if live.hard != want.hard:
            live.hard = want.hard
            return True
        return False
