"""TpuJob controller: gang-schedules a training job onto a TPU slice.

The platform's core new CRD (SURVEY.md §7.3), replacing the reference's
TFJob+openmpi pair. Differences by design:

- The unit of scheduling is a *slice* (ICI domain), not N interchangeable
  GPU pods. One worker pod per TPU-VM host, all-or-nothing.
- Worker wiring is the JAX distributed contract (coordinator address +
  process id + process count env) instead of TF_CONFIG's cluster JSON
  (reference: tf-controller-examples/tf-cnn/launcher.py:68-80) or the MPI
  sidecar's file signals (components/openmpi-controller/controller/
  controller.py:9-14).
- Placement is expressed as GKE TPU node selectors derived from the typed
  slice catalogue — replacing nvidia.com/gpu limits
  (jupyter-web-app .../utils.py:390-443).
- Failure policy is gang-level: any worker failing restarts the whole gang
  from the latest checkpoint (auto-resume contract of
  kubeflow_tpu.train.CheckpointService), up to max_restarts — the
  preemption story TPU pods require (SURVEY.md §5 Failure detection).
- Multislice (num_slices > 1) adds the DCN/megascale env so XLA routes
  inter-slice collectives over DCN.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.core import (
    Container,
    EnvVar,
    Pod,
    PodSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubeflow_tpu.controlplane.api.meta import (
    Condition,
    ObjectMeta,
    OwnerReference,
    set_condition,
)
from kubeflow_tpu.controlplane.api.types import TpuJob
from kubeflow_tpu.controlplane.runtime import (
    Controller,
    EventRecorder,
    InMemoryApiServer,
    NotFoundError,
    Result,
    create_or_update,
)
from kubeflow_tpu.topology import AxisSpec, get_slice, plan_mesh
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry

COORDINATOR_PORT = 8476
log = get_logger("tpujob")

JOB_LABEL = "tpu.kubeflow.org/job-name"
REPLICA_LABEL = "tpu.kubeflow.org/replica-index"

# Pod status.message marker a slice preemption stamps on its victims
# (written by chaos.SlicePreemptor and, in a cluster deployment, by the
# node-event relay). The controller keys its restart-vs-fail policy and
# budget accounting off this marker.
PREEMPTION_MESSAGE = "preempted: TPU slice reclaimed"


class TpuJobController(Controller):
    NAME = "tpujob"
    WATCH_KINDS = ("TpuJob", "Pod")

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        # Schedulable capacity: slice_type -> number of concurrently
        # allocatable slices. None = unbounded (tests / single-tenant).
        capacity: Optional[Dict[str, int]] = None,
        # Per-chip HBM fit check at admission (topology/capacity.py).
        hbm_check: bool = True,
        # Topology-aware gang scheduler (scheduler.GangScheduler). When
        # set, it owns status.slice_assignment for the slice types its
        # fleet manages: placement, priority preemption and restart
        # adoption all go through it; the admission ledger stays the
        # quota gate (and the capacity gate for unmanaged types).
        scheduler=None,
        # Cross-shard admission ledger client (controlplane.ledger): the
        # CLUSTER capacity authority behind the leader lease. When set,
        # slice-capacity reservations route through it instead of the
        # local capacity map, so two shards cannot double-admit.
        ledger=None,
        # How long a blocked (quota/capacity/unschedulable) gang parks
        # before retrying. Logical-time drivers (the schedule storm) park
        # effectively forever and retry via ControllerManager.kick_timers
        # — real-time park timers maturing INSIDE a long drain would
        # treadmill it.
        requeue_pending_s: float = 5.0,
    ):
        super().__init__(api, registry)
        self.capacity = capacity
        self.hbm_check = hbm_check
        self.scheduler = scheduler
        self.ledger = ledger
        self.requeue_pending_s = requeue_pending_s
        # (namespace, name) -> uid of gangs that hold scheduler units or
        # ledger reservations — releases must survive object deletion,
        # when reconcile only has the key.
        self._gang_uids: Dict[Tuple[str, str], str] = {}
        # (model, slice, slices, mesh, batch, seq, mu, model_kw) -> verdict;
        # reconcile re-enters constantly, eval_shape only needs to run once
        # per distinct spec.
        self._hbm_cache: Dict[tuple, Optional[str]] = {}
        # Admission serialization (ISSUE 5): the quota/capacity gates are
        # cross-key check-then-act — each job lists OTHER jobs' phases and
        # then writes only its OWN status, so resourceVersion conflicts
        # never detect two jobs admitting at once. Per-key serialization
        # doesn't cover that, so with workers>1 the whole gate runs under
        # this lock and an admitted-but-not-yet-visible job holds a
        # *reservation* (uid -> (namespace, slice_type, num_slices,
        # chips)) counted by later checks until the store itself shows the
        # job in an in-use phase.
        self._admission_lock = threading.Lock()
        self._admission_reserved: Dict[str, Tuple[str, str, int, int]] = {}
        self.recorder = EventRecorder(api, self.NAME)
        self.metrics_restarts = registry.counter(
            "kftpu_tpujob_gang_restarts_total", "Gang restarts", ("reason",)
        )
        self.metrics_resizes = registry.counter(
            "kftpu_tpujob_gang_resizes_total",
            "Elastic gang resizes (zero-downtime shrink/grow, "
            "no restart budget)", ("direction",)
        )

    # ------------- naming -------------

    @staticmethod
    def worker_name(job: str, i: int) -> str:
        return f"{job}-worker-{i}"

    @staticmethod
    def service_name(job: str) -> str:
        return f"{job}-workers"

    @staticmethod
    def _replica_index(pod) -> int:
        """The worker's gang index from its REPLICA_LABEL; -1 for a pod
        the label does not place (never ours / corrupted)."""
        try:
            return int(pod.metadata.labels.get(REPLICA_LABEL, "-1"))
        except ValueError:
            return -1

    # ------------- reconcile -------------

    def reconcile(self, namespace: str, name: str) -> Result:
        job = self.api.try_get("TpuJob", name, namespace)
        if job is None:
            self._release_gang_key((namespace, name))
            return Result()  # cascade GC removed dependents
        if job.metadata.deletion_timestamp is not None:
            self._release_gang(job)
            return Result()
        if job.status.phase in ("Succeeded", "Failed"):
            self._release_gang(job)
            return Result()

        # 1. Validate the topology request.
        try:
            st = get_slice(job.spec.slice_type)
            m = job.spec.mesh
            plan = plan_mesh(
                st,
                AxisSpec(dp=m.dp, pp=m.pp, fsdp=m.fsdp, tp=m.tp, sp=m.sp,
                         ep=m.ep),
            )
        except (KeyError, ValueError) as e:
            return self._fail_invalid(job, str(e))

        # 1a. Elastic bounds (ISSUE 11): a resize contract that cannot
        # hold is a permanent spec error, rejected at admission like a
        # bad mesh — never discovered mid-shrink.
        el = job.spec.elastic
        if el is not None:
            if not (1 <= el.min_slices <= job.spec.num_slices
                    <= el.max_slices):
                return self._fail_invalid(
                    job,
                    f"elastic bounds must satisfy 1 <= min_slices "
                    f"({el.min_slices}) <= num_slices "
                    f"({job.spec.num_slices}) <= max_slices "
                    f"({el.max_slices})",
                    reason="InvalidElasticSpec")
            if job.spec.preemption_policy != "restart":
                return self._fail_invalid(
                    job,
                    "elastic gangs require preemption_policy=restart "
                    "(shrink-instead-of-restart contradicts "
                    f"{job.spec.preemption_policy!r})",
                    reason="InvalidElasticSpec")

        # 1b. HBM fit gate: a registry-model job whose state + activations
        # can't fit the slice's per-chip HBM is rejected NOW (permanent
        # failure), not discovered as an OOM mid-schedule. The reference's
        # equivalent knob was a GPU limit string with no semantics
        # (jupyter-web-app utils.py:390-443); XLA's static memory program
        # lets admission do real accounting (topology/capacity.py).
        if self.hbm_check and job.spec.model:
            err = self._hbm_blocked(job, st)
            if err:
                return self._fail_invalid(job, err,
                                          reason="CapacityExceeded")

        # 2. Quota + capacity gates (gang admission: all or nothing).
        blocked = self._admission_blocked(job, st)
        # 2b. Placement (ISSUE 8): the admission ledger said "may run";
        # the scheduler decides WHERE — a concrete slice set — and may
        # preempt lower-priority gangs to make room. A gang that cannot
        # place parks Pending exactly like a capacity-blocked one.
        if blocked is None and self.scheduler is not None \
                and self.scheduler.manages(job.spec.slice_type):
            blocked = self._schedule_gang(job)
            if blocked is not None:
                # A parked gang must not keep holding admission capacity
                # it cannot use (units stay free for placeable peers).
                self._drop_reservation(job.metadata.uid)
                if self.ledger is not None:
                    self.ledger.release(job.metadata.uid)
        if blocked:
            import copy

            prev = copy.deepcopy(job.status)
            job.status.phase = "Pending"
            job.status.conditions = set_condition(
                job.status.conditions,
                Condition(type="Admitted", status="False", reason=blocked[0],
                          message=blocked[1]),
            )
            if job.status != prev:
                self.api.update_status(job)
            return Result(requeue_after=self.requeue_pending_s)

        # Elastic gangs run at status.current_slices (resized width);
        # fixed gangs at spec.num_slices. Every pod-facing computation
        # below — world size, worker count, coordinator env — follows the
        # CURRENT width, republished on every resize.
        n_hosts = st.num_hosts * self._gang_width(job)

        # 3. Headless service for gang DNS (worker-0 is the coordinator;
        # the reference used one headless service per TFJob replica).
        svc = Service(
            metadata=ObjectMeta(
                name=self.service_name(name), namespace=namespace,
                labels={JOB_LABEL: name},
                owner_references=[self._owner_ref(job)],
            ),
            spec=ServiceSpec(
                selector={JOB_LABEL: name},
                cluster_ip="None",
                ports=[ServicePort(name="coordinator",
                                   port=COORDINATOR_PORT,
                                   target_port=COORDINATOR_PORT)],
            ),
        )
        create_or_update(self.api, svc)

        coordinator = (
            f"{self.worker_name(name, 0)}.{self.service_name(name)}"
            f".{namespace}:{COORDINATOR_PORT}"
        )

        # 4. Gang pods: one per TPU-VM host. After a gang restart, hold the
        # backoff BEFORE recreating — watch events from the teardown would
        # otherwise re-enter reconcile and respawn the gang instantly (real
        # worker processes then race the dying generation for the
        # coordinator port).
        if job.status.phase == "Restarting":
            remaining = (
                job.status.last_restart_time + job.spec.backoff_seconds
                - time.time()
            )
            if remaining > 0:
                return Result(requeue_after=remaining)
        for i in range(n_hosts):
            pod = self._worker_pod(job, st, plan, i, n_hosts, coordinator)
            create_or_update(self.api, pod, copy_fields=self._pod_copy)

        # 5. Aggregate status.
        return self._update_status(job, n_hosts, coordinator)

    # ------------- admission -------------

    #: Phases that hold slice capacity / chip quota.
    IN_USE_PHASES = ("Scheduling", "Starting", "Running", "Restarting",
                     "Resizing")

    def _admission_blocked(self, job: TpuJob, st) -> Optional[tuple]:
        """Gang admission (all or nothing). The whole check-then-reserve
        runs under one lock: with a reconcile worker pool two jobs
        checking concurrently would each see the other still Pending and
        both admit past cap/quota — no ConflictError fires because each
        writes only its own status. An admitted job holds a reservation
        until the store shows it in an in-use phase."""
        chips = st.num_chips * job.spec.num_slices
        # Quota specs are read outside the lock (the lock protects the
        # job-phase check-then-act, not rarely-changing quota objects).
        quotas = [
            rq for rq in self.reader.list("ResourceQuota",
                                          namespace=job.metadata.namespace,
                                          copy=False)
            if int(rq.hard.get("google.com/tpu", "0") or 0) > 0
        ]
        if not quotas and self.capacity is None and self.ledger is None:
            # No gate configured (the unbounded dev/bench path): skip the
            # lock, the cluster-wide job list and the ledger — otherwise
            # every reconcile across the worker pool serializes here for
            # nothing.
            return None
        with self._admission_lock:
            blocked = self._admission_blocked_locked(job, chips, quotas)
            if blocked is None:
                self._admission_reserved[job.metadata.uid] = (
                    job.metadata.namespace, job.spec.slice_type,
                    job.spec.num_slices, chips,
                )
            else:
                # A blocked job parks Pending: it must not keep holding
                # capacity it admitted for in an earlier pass.
                self._admission_reserved.pop(job.metadata.uid, None)
        if blocked is None and self.ledger is not None and not (
                self.scheduler is not None
                and self.scheduler.manages(job.spec.slice_type)):
            # Cluster slice capacity through the cross-shard ledger (the
            # leader-lease authority): OUTSIDE the local lock — the
            # ledger serializes itself, and a slow leader failover must
            # stall only this key, not every admission in the process.
            # Scheduler-managed types skip the ledger exactly like the
            # local capacity count above: the fleet's unit accounting is
            # the capacity gate there, and a ledger reservation held by
            # every running victim would block the preemption path
            # before the scheduler ever saw the high-priority gang.
            self._remember_gang((job.metadata.namespace,
                                 job.metadata.name), job.metadata.uid)
            verdict = self.ledger.try_reserve(
                job.metadata.uid, job.spec.slice_type, job.spec.num_slices)
            if verdict is not None:
                self._drop_reservation(job.metadata.uid)
                blocked = ("InsufficientCapacity", verdict)
        return blocked

    def _drop_reservation(self, uid: str) -> None:
        with self._admission_lock:
            self._admission_reserved.pop(uid, None)

    def _admission_blocked_locked(self, job: TpuJob, chips: int,
                                  quotas: List) -> Optional[tuple]:
        if self.capacity is not None:
            # The capacity gate is cluster-wide by definition.
            all_jobs = self.reader.list("TpuJob", copy=False)
        else:
            # Quota-only: keep the namespaced read the old gate did —
            # this scan runs under the one lock every worker must pass
            # through. Namespaces holding reservations (few, short-lived)
            # are added so pruning still sees those jobs' phases.
            ns_needed = {job.metadata.namespace}
            ns_needed.update(
                ns for ns, _, _, _ in self._admission_reserved.values())
            all_jobs = []
            for ns in sorted(ns_needed):
                all_jobs.extend(
                    self.reader.list("TpuJob", namespace=ns, copy=False))
        by_uid = {o.metadata.uid: o for o in all_jobs}
        # Prune reservations: redundant once the store shows the job
        # in-use (counted from its phase below), dead once terminal/gone.
        for uid in list(self._admission_reserved):
            o = by_uid.get(uid)
            if o is None or o.status.phase in self.IN_USE_PHASES \
                    or o.status.phase in ("Succeeded", "Failed"):
                del self._admission_reserved[uid]
        reserved = [r for uid, r in self._admission_reserved.items()
                    if uid != job.metadata.uid]
        # Per-namespace TPU chip quota from ResourceQuota (emitted by the
        # profile controller from Profile.spec.tpu_chip_quota). The used
        # tally depends only on the namespace + ledger, not the quota
        # object — computed once, not per rq (this runs under the one
        # lock every worker must pass through).
        if quotas:
            used = sum(c for ns, _, _, c in reserved
                       if ns == job.metadata.namespace)
            for other in all_jobs:
                if other.metadata.namespace != job.metadata.namespace \
                        or other.metadata.name == job.metadata.name:
                    continue
                if other.status.phase in self.IN_USE_PHASES:
                    try:
                        used += (
                            get_slice(other.spec.slice_type).num_chips
                            * other.spec.num_slices
                        )
                    except KeyError:
                        pass
            for rq in quotas:
                hard = int(rq.hard.get("google.com/tpu", "0") or 0)
                if used + chips > hard:
                    return (
                        "QuotaExceeded",
                        f"needs {chips} chips, {hard - used} available "
                        "in quota",
                    )
        # Cluster slice capacity. Skipped for slice types the gang
        # scheduler's fleet manages: there the fleet's unit accounting IS
        # the capacity gate (counting here too would deadlock preemption
        # — evicted victims still sit in an in-use phase while the
        # higher-priority gang admits into their freed units).
        if self.capacity is not None and not (
                self.scheduler is not None
                and self.scheduler.manages(job.spec.slice_type)):
            cap = self.capacity.get(job.spec.slice_type, 0)
            in_use = sum(
                o.spec.num_slices
                for o in all_jobs
                if o.metadata.uid != job.metadata.uid
                and o.spec.slice_type == job.spec.slice_type
                and o.status.phase in self.IN_USE_PHASES
            )
            in_use += sum(n for _, s, n, _ in reserved
                          if s == job.spec.slice_type)
            if in_use + job.spec.num_slices > cap:
                return (
                    "InsufficientCapacity",
                    f"{in_use}/{cap} {job.spec.slice_type} slices in use",
                )
        return None

    # ------------- elastic width -------------

    @staticmethod
    def _gang_width(job: TpuJob) -> int:
        """The gang's CURRENT logical width: elastic gangs run at
        ``status.current_slices`` once set (shrunk/grown/shrink-to-fit
        placed); everything else — and a not-yet-placed elastic gang —
        at ``spec.num_slices``."""
        if job.spec.elastic is not None and job.status.current_slices > 0:
            return job.status.current_slices
        return job.spec.num_slices

    # ------------- scheduling (ISSUE 8) -------------

    def _schedule_gang(self, job: TpuJob) -> Optional[tuple]:
        """Hand the admitted gang to the scheduler. Returns None when the
        gang holds (or just received) a slice set, else the
        ``(reason, message)`` that parks it Pending."""
        import copy

        uid = job.metadata.uid
        self._remember_gang((job.metadata.namespace, job.metadata.name),
                            uid)
        if self.scheduler.assignment_of(uid) is not None:
            return None
        if job.status.slice_assignment:
            # Restart adoption: a controller-manager restart (snapshot
            # load / WAL replay) must re-pin the EXACT recorded units,
            # never migrate.
            if self.scheduler.adopt(job) is not None:
                return None
            # Units gone or taken: an evicted gang whose preemption
            # branch has not cleared status yet. If its pods carry the
            # failure evidence, let the failure path run — re-placing a
            # failed gang here would race its own teardown.
            pods = self.reader.list(
                "Pod", namespace=job.metadata.namespace,
                label_selector={JOB_LABEL: job.metadata.name},
                copy=False,
            )
            if any(p.status.phase == "Failed" for p in pods):
                return None
        rendered, blocked = self.scheduler.assign(
            job,
            jobs=self.reader.list("TpuJob", copy=False),
            api=self.api,
            recorder=self.recorder,
        )
        if blocked is not None:
            return blocked
        prev = copy.deepcopy(job.status)
        job.status.slice_assignment = rendered
        if job.spec.elastic is not None:
            # Shrink-to-fit placement: the scheduler may have placed the
            # gang below spec.num_slices (down to min_slices); the
            # current width IS the placed width (the ElasticController
            # grows it back toward max_slices as capacity frees).
            from kubeflow_tpu.scheduler.placement import parse_assignment

            units = parse_assignment(rendered) or []
            if units:
                job.status.current_slices = len(units)
        if job.status.phase in ("", "Pending"):
            job.status.phase = "Scheduling"
        job.status.conditions = set_condition(
            job.status.conditions,
            Condition(type="Admitted", status="True", reason="Scheduled",
                      message=rendered),
        )
        if job.status != prev:
            self.api.update_status(job)
        return None

    def _release_uid(self, uid: str) -> None:
        """THE one release sequence: admission reservation, scheduler
        units, ledger reservation. Idempotent."""
        self._drop_reservation(uid)
        if self.scheduler is not None:
            self.scheduler.release(uid)
        if self.ledger is not None:
            self.ledger.release(uid)

    def _remember_gang(self, key: Tuple[str, str], uid: str) -> None:
        """Track key -> uid for release-after-deletion. A DIFFERENT uid
        already remembered under the key means the object was deleted
        and recreated between reconciles (the workqueue coalesced both
        events, so the job-is-None release never ran): free everything
        the ghost uid still holds before it leaks."""
        old = self._gang_uids.get(key)
        if old is not None and old != uid:
            self._release_uid(old)
        self._gang_uids[key] = uid

    def _release_gang(self, job: TpuJob) -> None:
        """Free everything a finished/removed gang holds."""
        self._gang_uids.pop(
            (job.metadata.namespace, job.metadata.name), None)
        self._release_uid(job.metadata.uid)

    def _release_gang_key(self, key: Tuple[str, str]) -> None:
        """Release by (namespace, name) after the object is gone —
        reconcile then only has the key; the uid was remembered when the
        gang admitted."""
        uid = self._gang_uids.pop(key, None)
        if uid is not None:
            self._release_uid(uid)

    # ------------- pod template -------------

    def _owner_ref(self, job: TpuJob) -> OwnerReference:
        return OwnerReference(
            kind="TpuJob", name=job.metadata.name, uid=job.metadata.uid
        )

    def _worker_pod(
        self, job: TpuJob, st, plan, index: int, n_hosts: int, coordinator: str
    ) -> Pod:
        name = job.metadata.name
        mesh_json = json.dumps(plan.axes.as_dict())
        slice_id = index // st.num_hosts
        # Failure AND preemption restarts bump the gang generation — both
        # must invalidate the previous generation's pods.
        generation = job.status.restarts + job.status.preemptions
        env = [
            EnvVar("KFTPU_COORDINATOR_ADDRESS", coordinator),
            EnvVar("KFTPU_NUM_PROCESSES", str(n_hosts)),
            EnvVar("KFTPU_PROCESS_ID", str(index)),
            EnvVar("KFTPU_SLICE_TYPE", st.name),
            EnvVar("KFTPU_MESH", mesh_json),
            EnvVar("KFTPU_ATTN_IMPL", job.spec.attn_impl),
            EnvVar("KFTPU_MODEL", job.spec.model),
            EnvVar("KFTPU_CHECKPOINT_DIR", job.spec.checkpoint_dir),
            EnvVar("KFTPU_RESTART_COUNT", str(generation)),
        ]
        if job.spec.trace_dir:
            env.append(EnvVar("KFTPU_TRACE_DIR", job.spec.trace_dir))
        if job.spec.num_slices > 1:
            # Multislice: DCN-routed inter-slice collectives (megascale).
            env += [
                EnvVar("MEGASCALE_NUM_SLICES", str(job.spec.num_slices)),
                EnvVar("MEGASCALE_SLICE_ID", str(slice_id)),
                EnvVar("MEGASCALE_COORDINATOR_ADDRESS", coordinator),
            ]
        env += list(job.spec.env)

        container = Container(
            name="worker",
            image=job.spec.image or "kubeflow-tpu/runtime:latest",
            command=list(job.spec.command)
            or ["python", "-m", "kubeflow_tpu.train.runner"],
            args=list(job.spec.args),
            env=env,
            ports=[COORDINATOR_PORT],
            resources={
                st.resource_name(): str(st.chips_per_host),
                "memory": "64Gi",
            },
        )
        labels = {
            JOB_LABEL: name,
            REPLICA_LABEL: str(index),
            "restart-generation": str(generation),
        }
        if job.status.phase == "Resizing":
            # Elastic resize: the gang's world never cold-restarted —
            # workers (re)created mid-resize join an already-initialized
            # world (the VirtualFlow virtual-node handoff) and skip the
            # kubelet's cold-start warmup model.
            labels["warm-start"] = "true"
        return Pod(
            metadata=ObjectMeta(
                name=self.worker_name(name, index),
                namespace=job.metadata.namespace,
                labels=labels,
                owner_references=[self._owner_ref(job)],
            ),
            spec=PodSpec(
                containers=[container],
                node_selector=st.node_selectors(),
                restart_policy="Never",
                subdomain=self.service_name(name),
                hostname=self.worker_name(name, index),
                scheduler_hints={
                    "slice-group": f"{name}-{slice_id}",
                    "gang-size": str(n_hosts),
                },
            ),
        )

    @staticmethod
    def _pod_copy(live: Pod, want: Pod) -> bool:
        """Pods are mostly immutable; only re-label — EXCEPT
        restart-generation, which is the pod's identity: it records which
        gang generation created the pod and is how a resumed teardown
        tells survivors of the old generation from freshly recreated
        workers. Overwriting it here let a recreate pass that raced an
        interrupted teardown relabel old-generation Running workers as
        current, silently downgrading the all-or-nothing gang restart to
        a single-pod restart."""
        changed = False
        want_labels = dict(want.metadata.labels)
        gen = live.metadata.labels.get("restart-generation")
        if gen is not None:
            want_labels["restart-generation"] = gen
        if live.metadata.labels != want_labels:
            live.metadata.labels = want_labels
            changed = True
        return changed

    # ------------- status -------------

    def _update_status(self, job: TpuJob, n_hosts: int, coordinator: str) -> Result:
        import copy

        # Informer-cache read, zero-copy: pods are only *read* here (and
        # deleted by name in _teardown_gang) — never mutated in place.
        pods = self.reader.list(
            "Pod", namespace=job.metadata.namespace,
            label_selector={JOB_LABEL: job.metadata.name},
            copy=False,
        )
        states = {p.metadata.name: p.status.phase for p in pods}
        prev_status = copy.deepcopy(job.status)
        job.status.worker_states = states
        # Lift worker-0's termination report (K8s terminationMessagePath
        # channel, written by train.runner) into job metrics — consumed by
        # the StudyJob controller as the trial objective.
        w0 = self.worker_name(job.metadata.name, 0)
        for p in pods:
            if p.metadata.name == w0 and p.status.termination_message:
                try:
                    msg = json.loads(p.status.termination_message)
                    job.status.metrics = {
                        k: float(v) for k, v in msg.items()
                        if isinstance(v, (int, float))
                    }
                except (ValueError, AttributeError):
                    pass
        job.status.coordinator_address = coordinator
        if not (self.scheduler is not None
                and self.scheduler.manages(job.spec.slice_type)):
            # Legacy shape-only assignment; with a scheduler the field
            # carries the concrete slice set _schedule_gang placed. The
            # CURRENT width — an elastic resize republishes it.
            job.status.slice_assignment = (
                f"{job.spec.slice_type}x{self._gang_width(job)}"
            )

        phases = list(states.values())
        n_running = sum(1 for p in phases if p == "Running")
        n_failed = sum(1 for p in phases if p == "Failed")
        n_succeeded = sum(1 for p in phases if p == "Succeeded")

        requeue: Optional[float] = None
        if n_failed > 0:
            # Per-pod classification: only marker-carrying failures are
            # preemptions. A genuine worker crash that coincides with a
            # slice preemption must still consume the restart budget —
            # any() over the gang would launder crashes as preemptions.
            n_preempted = sum(
                1 for p in pods
                if p.status.phase == "Failed"
                and p.status.message == PREEMPTION_MESSAGE
            )
            crash_failures = n_failed - n_preempted
            if job.status.phase == "Restarting":
                # Restart accounting already committed; a previous
                # teardown was interrupted — finish it without
                # re-counting (idempotent re-entry).
                return self._teardown_gang(job, pods, stale_only=True)
            if job.status.phase == "Resizing":
                doomed = set(job.status.resize_doomed)
                stale = [p for p in pods if p.metadata.name in doomed]
                fresh = [p for p in pods
                         if p.status.phase == "Failed"
                         and p.metadata.name not in doomed]
                if stale or not fresh:
                    # Resize accounting already committed (the resize
                    # status write IS the commit point); finish clearing
                    # the stale pods without re-counting.
                    return self._teardown_resize(job, pods)
                # The owed teardown is done but NEW failures arrived
                # mid-resize (an eviction racing the republish): phase
                # Resizing is no shield — fall through and classify
                # them like any other failure.
            if crash_failures == 0 and job.spec.elastic is not None:
                # Elastic shrink (ISSUE 11): keep the surviving slices,
                # resize the gang instead of restarting it — as long as
                # the survivors satisfy min_slices. Below that floor the
                # preemption falls through to the ordinary restart path.
                resized = self._resize_shrink(job, pods, n_hosts)
                if resized is not None:
                    return resized
            if crash_failures == 0 and job.spec.preemption_policy == "fail":
                job.status.phase = "Failed"
                job.status.completion_time = time.time()
                self.recorder.event(
                    job, "Warning", "JobFailed",
                    "slice preempted and preemption_policy=fail",
                )
            elif crash_failures == 0:
                # Preemption is not the job's fault: reschedule onto
                # surviving capacity without consuming the max_restarts
                # budget (the gang re-enters admission, so a reclaimed
                # slice parks it Pending until capacity returns).
                job.status.preemptions += 1
                # The old slice set is gone (reclaimed by hardware, the
                # scheduler, or the defragmenter): clear the assignment
                # so the restart re-places instead of re-pinning.
                if self.scheduler is not None \
                        and self.scheduler.manages(job.spec.slice_type):
                    job.status.slice_assignment = ""
                    self.scheduler.release(job.metadata.uid)
                # An elastic gang that fell below min_slices restarts
                # like any other — and re-places from spec width again
                # (shrink-to-fit decides the fresh current width).
                job.status.current_slices = 0
                self._commit_restart_status(job)
                self.metrics_restarts.inc(reason="preempted")
                self.recorder.event(
                    job, "Warning", "SlicePreempted",
                    f"slice preempted; reschedule {job.status.preemptions}, "
                    f"resuming from {job.spec.checkpoint_dir or 'scratch'}",
                )
                return self._teardown_gang(job, pods)
            elif job.status.restarts < job.spec.max_restarts:
                job.status.restarts += 1
                self._commit_restart_status(job)
                self.metrics_restarts.inc(reason="worker-failed")
                self.recorder.event(
                    job, "Warning", "GangRestart",
                    f"worker failure; restart {job.status.restarts}/"
                    f"{job.spec.max_restarts}, resuming from "
                    f"{job.spec.checkpoint_dir or 'scratch'}",
                )
                return self._teardown_gang(job, pods)
            else:
                job.status.phase = "Failed"
                job.status.completion_time = time.time()
                self.recorder.event(
                    job, "Warning", "JobFailed",
                    f"exceeded max_restarts={job.spec.max_restarts}",
                )
        elif len(phases) == n_hosts and n_succeeded == n_hosts:
            job.status.phase = "Succeeded"
            job.status.completion_time = time.time()
            self.recorder.event(job, "Normal", "JobSucceeded", "all workers done")
        elif len(phases) == n_hosts and n_running == n_hosts:
            job.status.phase = "Running"
            if job.status.start_time == 0.0:
                job.status.start_time = time.time()
                self.recorder.event(
                    job, "Normal", "GangRunning",
                    f"{n_hosts} workers on {job.status.slice_assignment}",
                )
        elif job.status.phase in ("Restarting", "Resizing") \
                and len(phases) < n_hosts:
            requeue = 0.5  # pods still terminating; recreate next pass
        elif job.status.phase == "Resizing":
            pass  # pods recreated at the new width; waiting for Running
        else:
            job.status.phase = "Starting"

        job.status.conditions = set_condition(
            job.status.conditions,
            Condition(
                type="Admitted", status="True", reason="Scheduled",
                message=job.status.slice_assignment,
            ),
        )
        job.status.conditions = set_condition(
            job.status.conditions,
            Condition(
                type="Running",
                status="True" if job.status.phase == "Running" else "False",
                reason=job.status.phase,
                message=f"{n_running}/{n_hosts} workers running",
            ),
        )
        # Write only on real change: an unconditional status write would emit
        # MODIFIED on every reconcile and livelock the watch loop.
        if job.status != prev_status:
            self.api.update_status(job)
        return Result(requeue_after=requeue)

    def _commit_restart_status(self, job: TpuJob) -> None:
        """Persist the restart accounting BEFORE any pod is torn down: a
        conflicting status write then requeues with the world untouched,
        while a teardown interrupted AFTER the commit re-enters through
        the idempotent phase=='Restarting' path without re-counting.
        (Committing after deletion lost the restarts/preemptions bump
        whenever the write failed — a crash-looping job whose status
        writes kept conflicting could restart past max_restarts.)"""
        job.status.phase = "Restarting"
        job.status.last_restart_time = time.time()
        self.api.update_status(job)

    # ------------- elastic resize (ISSUE 11) -------------

    def _resize_shrink(self, job: TpuJob, pods,
                       n_hosts: int) -> Optional[Result]:
        """Shrink the gang onto its surviving slices: a preemption hit
        one or more slice groups of an elastic gang and enough survive to
        satisfy ``min_slices``. The gang keeps its surviving units,
        ``status.slice_assignment`` and the world size republish at the
        new width, and the job resumes from the newest COMPLETE step in
        the checkpoint catalog — a resize (``status.resizes``), never a
        restart: no ``max_restarts`` or ``status.preemptions`` bump, no
        re-admission queue, no backoff hold. Returns None when the
        survivors fall below the floor (the ordinary restart path then
        runs)."""
        st = get_slice(job.spec.slice_type)
        width = n_hosts // max(st.num_hosts, 1)
        lost = set()
        for p in pods:
            if p.status.phase != "Failed" \
                    or p.status.message != PREEMPTION_MESSAGE:
                continue
            idx = self._replica_index(p)
            if 0 <= idx < n_hosts:
                lost.add(idx // st.num_hosts)
        keep = [g for g in range(width) if g not in lost]
        if not lost or len(keep) < job.spec.elastic.min_slices:
            return None
        # Commit the resize BEFORE any pod is touched (the restart
        # discipline of _commit_restart_status): a conflicting status
        # write requeues with the world untouched, while a teardown
        # interrupted AFTER the commit re-enters through the idempotent
        # phase == "Resizing" path without re-counting.
        job.status.resizes += 1
        job.status.current_slices = len(keep)
        rendered = None
        if self.scheduler is not None \
                and self.scheduler.manages(job.spec.slice_type):
            from kubeflow_tpu.scheduler.placement import parse_assignment

            units = parse_assignment(job.status.slice_assignment) or []
            keep_units = [units[g] for g in keep if g < len(units)]
            if keep_units:
                rendered = self.scheduler.shrink(
                    job.metadata.uid, keep_units)
        job.status.slice_assignment = rendered or (
            f"{job.spec.slice_type}x{len(keep)}")
        step = self._catalog_step(job)
        if step is not None:
            job.status.resumed_from_step = step
        job.status.phase = "Resizing"
        # Record the owed teardown IN the commit: the Resizing re-entry
        # deletes exactly these and can therefore tell a fresh eviction
        # racing the resize from its own stale pods.
        new_n_hosts = len(keep) * st.num_hosts
        doomed = set(job.status.resize_doomed)
        for p in pods:
            idx = self._replica_index(p)
            if p.status.phase == "Failed" or idx < 0 \
                    or idx >= new_n_hosts:
                doomed.add(p.metadata.name)
        job.status.resize_doomed = sorted(doomed)
        self.api.update_status(job)
        self.metrics_resizes.inc(direction="shrink")
        self.recorder.event(
            job, "Warning", "ElasticShrink",
            f"slice preempted; gang resized {width}->{len(keep)} slices "
            f"(resize {job.status.resizes}), resuming from "
            + (f"step {step}" if step is not None
               else (job.spec.checkpoint_dir or "scratch")),
        )
        return self._teardown_resize(job, pods)

    def _teardown_resize(self, job: TpuJob, pods) -> Result:
        """Clear exactly the pods the committed resize owes
        (``status.resize_doomed``: the preempted groups' Failed pods and
        any survivor whose index fell off the renumbered world) plus any
        out-of-range straggler. Survivors inside the new range are NOT
        touched — that is the zero-downtime half of the resize contract.
        Failed pods go last so an interrupted teardown keeps its
        evidence (the ``_teardown_gang`` discipline); re-entry is keyed
        off phase == "Resizing" and the doomed ledger, never
        re-counted. Once every owed pod is gone the ledger clears, so a
        LATER failure is classified as the fresh event it is."""
        st = get_slice(job.spec.slice_type)
        n_hosts = st.num_hosts * self._gang_width(job)
        doomed_names = set(job.status.resize_doomed)
        doomed = []
        for p in pods:
            idx = self._replica_index(p)
            if p.metadata.name in doomed_names or idx < 0 \
                    or idx >= n_hosts:
                doomed.append(p)
        for p in sorted(doomed, key=lambda p: p.status.phase == "Failed"):
            try:
                self.api.delete("Pod", p.metadata.name,
                                p.metadata.namespace)
            except NotFoundError:
                pass
        if job.status.resize_doomed:
            # Every owed deletion issued: retire the ledger (a conflict
            # here just retries — the deletes above are idempotent).
            job.status.resize_doomed = []
            self.api.update_status(job)
        # Zero-downtime: recreate the renumbered world NOW (no backoff —
        # the preemption cost a resize, not a restart window).
        return Result(requeue_after=0.0)

    def _catalog_step(self, job: TpuJob) -> Optional[int]:
        """Newest COMPLETE step in the job's checkpoint catalog entry —
        what a resized gang resumes from (torn/in-progress saves are
        skipped by the catalog, ckpt_catalog.latest_complete_step)."""
        if not job.spec.checkpoint_dir:
            return None
        from kubeflow_tpu.controlplane.ckpt_catalog import (
            latest_complete_step,
        )

        return latest_complete_step(job.spec.checkpoint_dir)

    def _teardown_gang(self, job: TpuJob, pods, *,
                       stale_only: bool = False) -> Result:
        """Tear down workers; the next reconcile recreates them with a
        bumped restart generation. Workers auto-resume from
        spec.checkpoint_dir (train.CheckpointService restore-latest
        contract). ``stale_only`` (the resumed-teardown path) spares pods
        of the current generation that a recreate pass already made."""
        generation = str(job.status.restarts + job.status.preemptions)
        if stale_only:
            pods = [
                p for p in pods
                if p.status.phase == "Failed"
                or p.metadata.labels.get("restart-generation") != generation
            ]
        # Delete the Failed pods LAST: if a transient API error interrupts
        # the teardown mid-way, the retry still sees the failure evidence
        # and resumes the restart instead of quietly backfilling the gang.
        for p in sorted(pods, key=lambda p: p.status.phase == "Failed"):
            try:
                self.api.delete("Pod", p.metadata.name, p.metadata.namespace)
            except NotFoundError:
                pass  # raced with cascade GC — already gone
        return Result(requeue_after=job.spec.backoff_seconds)

    def _fail_invalid(self, job: TpuJob, msg: str,
                      reason: str = "InvalidTopology") -> Result:
        job.status.phase = "Failed"
        job.status.conditions = set_condition(
            job.status.conditions,
            Condition(type="Admitted", status="False",
                      reason=reason, message=msg),
        )
        self.api.update_status(job)
        self.recorder.event(job, "Warning", reason, msg)
        return Result()

    def _hbm_blocked(self, job: TpuJob, st) -> Optional[str]:
        """Analytic per-chip HBM estimate for registry-model jobs; returns
        a rejection message when the job cannot fit. Estimator failures
        never block admission (fail open, loudly)."""
        from kubeflow_tpu.topology.capacity import (
            GiB,
            InvalidTrainingConfig,
            analytic_report,
        )

        env = {e.name: e.value for e in job.spec.env}
        n_hosts = st.num_hosts * job.spec.num_slices
        m = job.spec.mesh
        cache_key = (
            job.spec.model, job.spec.slice_type, job.spec.num_slices,
            (m.dp, m.pp, m.ep, m.fsdp, m.sp, m.tp),
            env.get("KFTPU_BATCH_PER_HOST", "8"),
            env.get("KFTPU_SEQ_LEN", "1024"),
            env.get("KFTPU_HPARAMS", ""),
            env.get("KFTPU_MODEL_KW", ""),
        )
        if cache_key in self._hbm_cache:
            return self._hbm_cache[cache_key]
        # Owner-fixable inputs parse in their own try: malformed JSON or
        # non-numeric env values are the job's fault and reject with an
        # actionable message — NOT fail-open material.
        try:
            hp = json.loads(env.get("KFTPU_HPARAMS", "{}") or "{}")
            model_kw = json.loads(env.get("KFTPU_MODEL_KW", "{}") or "{}")
            global_batch = int(
                env.get("KFTPU_BATCH_PER_HOST", "8")) * n_hosts
            seq_len = int(env.get("KFTPU_SEQ_LEN", "1024"))
            grad_accum = int(hp.get("grad_accum_steps", 1))
        except (ValueError, TypeError, AttributeError) as e:
            verdict = f"invalid training config: {e}"
            self._hbm_cache[cache_key] = verdict
            return verdict
        try:
            rep = analytic_report(
                job.spec.model, job.spec.slice_type,
                AxisSpec(dp=m.dp, pp=m.pp, ep=m.ep, fsdp=m.fsdp,
                         sp=m.sp, tp=m.tp),
                num_slices=job.spec.num_slices,
                global_batch=global_batch,
                seq_len=seq_len,
                mu_dtype=str(hp.get("mu_dtype", "")),
                optimizer=str(hp.get("optimizer", "adamw")),
                grad_accum=grad_accum,
                model_kw=model_kw,
            )
        except InvalidTrainingConfig as e:
            # Config contradictions (non-divisible grad_accum, unknown
            # optimizer names) are the job's fault: reject, the same
            # contract as mesh-validation failures above. Every OTHER
            # failure here is an estimator bug and stays fail-open.
            verdict = f"invalid training config: {e}"
            self._hbm_cache[cache_key] = verdict
            return verdict
        except Exception as e:  # noqa: BLE001 — estimator must fail open
            log.warning("hbm admission estimate failed",
                        kv={"job": job.metadata.name, "err": repr(e)})
            self._hbm_cache[cache_key] = None
            return None
        verdict = None
        if not rep.fits():
            verdict = (
                f"model {job.spec.model} needs ~{rep.total / GiB:.1f} "
                f"GiB/chip ({rep.params / GiB:.1f} params + "
                f"{rep.grads / GiB:.1f} grads + "
                f"{rep.opt_state / GiB:.1f} opt + "
                f"{rep.activations / GiB:.1f} activations) but "
                f"{job.spec.slice_type} has {rep.hbm_per_chip / GiB:.0f} "
                f"GiB/chip; use a larger slice, more model sharding, or "
                f"bf16 params/mu (KFTPU_MODEL_KW/KFTPU_HPARAMS). "
                f"Verify with: tpuctl plan --aot"
            )
        self._hbm_cache[cache_key] = verdict
        return verdict
