"""Control-plane process entrypoint: controllers against a live backend.

The deployment story the reference's controller images implement with
controller-runtime managers (notebook_controller.go main.go etc.): one
process that runs the platform's controllers against a cluster, exports
metrics, and reports its own availability.

  python -m kubeflow_tpu.controlplane.main \
      --backend kubectl [--kubectl-bin kubectl --context ctx] \
      --components tpujob,studyjob,notebook,profile,tensorboard,serving \
      --metrics-port 9090 --poll-interval 2

- backend ``memory`` is the dev loop (fresh in-memory apiserver);
  ``kubectl`` targets a real cluster through the adapter's poll-informer
  watches.
- the metrics port serves the Prometheus text exposition (the per-
  controller counters/heartbeats plus the availability prober's
  ``kftpu_availability``).
"""

from __future__ import annotations

import argparse
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Tuple

from kubeflow_tpu.controlplane.controllers import (
    NotebookController,
    ProfileController,
    ServingController,
    StudyJobController,
    TensorboardController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.prober import (
    AvailabilityProber,
    controller_target,
)
from kubeflow_tpu.controlplane.runtime import (
    ControllerManager,
    InMemoryApiServer,
)
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry

log = get_logger("controlplane")

CONTROLLERS = {
    "tpujob": TpuJobController,
    "studyjob": StudyJobController,
    "notebook": NotebookController,
    "profile": ProfileController,
    "tensorboard": TensorboardController,
    "serving": ServingController,
}


def build(args) -> Tuple[object, ControllerManager, AvailabilityProber,
                         MetricsRegistry]:
    """Wire the manager; separated from run() so tests can pump manually."""
    registry = MetricsRegistry()
    if args.backend == "kubectl":
        from kubeflow_tpu.controlplane.runtime.kubectl import KubectlApiServer

        api = KubectlApiServer(
            kubectl=args.kubectl_bin, context=args.context,
            poll_interval=args.poll_interval,
        )
    else:
        api = InMemoryApiServer()
    manager = ControllerManager(api)
    names = [c.strip() for c in args.components.split(",") if c.strip()]
    for name in names:
        cls = CONTROLLERS.get(name)
        if cls is None:
            raise SystemExit(
                f"unknown controller {name!r}; known: {sorted(CONTROLLERS)}"
            )
        manager.register(cls(api, registry))
    prober = AvailabilityProber(
        {ctl.NAME: controller_target(manager, ctl)
         for ctl in manager.controllers},
        registry,
        interval_s=args.probe_interval,
    )
    return api, manager, prober, registry


class _MetricsServer:
    def __init__(self, registry: MetricsRegistry, port: int):
        reg = registry

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = reg.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.httpd.shutdown()


def run(args) -> int:
    api, manager, prober, registry = build(args)
    if hasattr(api, "start_polling"):
        api.start_polling()
    manager.start()
    prober.start()
    metrics = None
    if args.metrics_port >= 0:
        metrics = _MetricsServer(registry, args.metrics_port)
        log.info("metrics serving", kv={"port": metrics.port})
    log.info("control plane up",
             kv={"backend": args.backend,
                 "controllers": len(manager.controllers)})
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        prober.stop()
        manager.stop()
        if hasattr(api, "stop_polling"):
            api.stop_polling()
        if metrics is not None:
            metrics.stop()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kftpu-controlplane")
    p.add_argument("--backend", choices=("memory", "kubectl"),
                   default="kubectl")
    p.add_argument("--kubectl-bin", default="kubectl")
    p.add_argument("--context", default="")
    p.add_argument("--components",
                   default="tpujob,studyjob,notebook,profile,tensorboard,"
                           "serving")
    p.add_argument("--poll-interval", type=float, default=2.0)
    p.add_argument("--probe-interval", type=float, default=30.0)
    p.add_argument("--metrics-port", type=int, default=9090,
                   help="-1 disables the metrics endpoint")
    return p


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
