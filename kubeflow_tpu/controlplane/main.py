"""Control-plane process entrypoint: controllers against a live backend.

The deployment story the reference's controller images implement with
controller-runtime managers (notebook_controller.go main.go etc.): one
process that runs the platform's controllers against a cluster, exports
metrics, and reports its own availability.

  python -m kubeflow_tpu.controlplane.main \
      --backend kubectl [--kubectl-bin kubectl --context ctx] \
      --components tpujob,studyjob,notebook,profile,tensorboard,serving \
      --metrics-port 9090 --poll-interval 2

- backend ``memory`` is the dev loop (fresh in-memory apiserver);
  ``kubectl`` targets a real cluster through the adapter's poll-informer
  watches.
- the metrics port serves the Prometheus text exposition (the per-
  controller counters/heartbeats plus the availability prober's
  ``kftpu_availability``).
"""

from __future__ import annotations

import argparse
from typing import Tuple

from kubeflow_tpu.controlplane.controllers import (
    NotebookController,
    ProfileController,
    ServingController,
    StudyJobController,
    TensorboardController,
    TpuJobController,
)
from kubeflow_tpu.controlplane.prober import (
    AvailabilityProber,
    controller_target,
)
from kubeflow_tpu.controlplane.runtime import ControllerManager
from kubeflow_tpu.controlplane.runtime.backend import (
    add_backend_args,
    build_backend,
    serve_forever,
)
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsHttpServer, MetricsRegistry

log = get_logger("controlplane")

CONTROLLERS = {
    "tpujob": TpuJobController,
    "studyjob": StudyJobController,
    "notebook": NotebookController,
    "profile": ProfileController,
    "tensorboard": TensorboardController,
    "serving": ServingController,
}


def build(args) -> Tuple[object, ControllerManager, AvailabilityProber,
                         MetricsRegistry]:
    """Wire the manager; separated from run() so tests can pump manually."""
    registry = MetricsRegistry()
    api = build_backend(args)
    manager = ControllerManager(api, workers=getattr(args, "workers", 1))
    names = [c.strip() for c in args.components.split(",") if c.strip()]
    for name in names:
        cls = CONTROLLERS.get(name)
        if cls is None:
            raise SystemExit(
                f"unknown controller {name!r}; known: {sorted(CONTROLLERS)}"
            )
        manager.register(cls(api, registry))
    prober = AvailabilityProber(
        {ctl.NAME: controller_target(manager, ctl)
         for ctl in manager.controllers},
        registry,
        interval_s=args.probe_interval,
    )
    return api, manager, prober, registry


def run(args) -> int:
    api, manager, prober, registry = build(args)
    if hasattr(api, "start_polling"):
        api.start_polling()
    manager.start()
    prober.start()
    metrics = None
    if args.metrics_port >= 0:
        metrics = MetricsHttpServer(registry, args.metrics_port)
        log.info("metrics serving", kv={"port": metrics.port})
    log.info("control plane up",
             kv={"backend": args.backend,
                 "controllers": len(manager.controllers)})
    serve_forever(
        prober.stop,
        manager.stop,
        getattr(api, "stop_polling", lambda: None),
        (metrics.stop if metrics is not None else (lambda: None)),
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="kftpu-controlplane")
    add_backend_args(p)
    p.add_argument("--components",
                   default="tpujob,studyjob,notebook,profile,tensorboard,"
                           "serving")
    p.add_argument("--probe-interval", type=float, default=30.0)
    p.add_argument("--metrics-port", type=int, default=9090,
                   help="-1 disables the metrics endpoint")
    p.add_argument("--workers", type=int, default=1,
                   help="reconcile worker-pool size (the "
                        "MaxConcurrentReconciles analogue): distinct keys "
                        "reconcile concurrently, a key never overlaps "
                        "itself; 1 = strictly serial dispatch")
    return p


def main(argv=None) -> int:
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
