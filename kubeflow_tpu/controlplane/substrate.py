"""Cloud-substrate provisioning seam — the ``Apply(PLATFORM)`` half.

The reference's kfctl server doesn't just apply K8s manifests: it first
creates the cluster substrate through GCP Deployment Manager and tears it
down with a resource-leak check
(reference bootstrap/cmd/bootstrap/app/kfctlServer.go:219-296,
testing/kfctl/kfctl_delete_test.py:44-71). Here the substrate is TPU
slice pools + CPU node pools, created through a typed provider plugin
BEFORE the platform's k8s-level apply and reclaimed on deployment delete:

- ``SubstrateProvider``: the seam — ``ensure_pools`` (idempotent create/
  update), ``deprovision`` (delete everything the deployment owns),
  ``list_resources`` (the leak check's source of truth).
- ``FakeSubstrateProvider``: the in-env implementation (no cloud, zero
  egress) with real provider semantics: slice types validated against
  the topology catalog, spec-diffing updates, per-deployment ownership.
  A GCP/AWS implementation replaces the pool-record store with TPU API /
  EC2 calls — the seam's shape is the contract (same pattern as the
  profile controller's two IAM plugins, controllers/profile.py).
- Finalizer guard: ``Platform.apply_config`` adds SUBSTRATE_FINALIZER to
  the PlatformConfig; ``Platform.delete_config`` deprovisions, LEAK-CHECKS
  (raises if anything the provider still tracks survives), and only then
  removes the finalizer — delete leaves nothing, provably.
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.types import SubstrateSpec
from kubeflow_tpu.utils import get_logger

log = get_logger("substrate")

SUBSTRATE_FINALIZER = "substrate.tpu.kubeflow.org"


class SubstrateError(Exception):
    pass


class SubstrateLeakError(SubstrateError):
    """Deprovision left resources behind — the delete contract is broken
    (reference kfctl_delete_test.py:44-71 greps for leaked DM resources).
    """


class SubstrateProvider:
    """Provider seam. Implementations own (deployment, pool) -> resource
    lifecycles; all methods are synchronous and idempotent."""

    KIND = ""

    def ensure_pools(self, deployment: str,
                     spec: SubstrateSpec) -> List[str]:
        """Create/update every pool in ``spec``; delete pools the spec no
        longer lists (the deployment owns exactly its spec). Returns the
        pool names now live. Must be idempotent."""
        raise NotImplementedError

    def validate_spec(self, spec: SubstrateSpec) -> None:
        """Raise SubstrateError if ``spec`` could never provision — a
        DRY check with no side effects, so callers can validate a new
        substrate before tearing an old one down."""
        raise NotImplementedError

    def deprovision(self, deployment: str) -> List[str]:
        """Delete everything the deployment owns; returns what was
        deleted."""
        raise NotImplementedError

    def list_resources(self, deployment: str) -> List[Dict[str, Any]]:
        """Everything the provider still tracks for the deployment — the
        leak check reads this after deprovision."""
        raise NotImplementedError


class FakeSubstrateProvider(SubstrateProvider):
    KIND = "fake"

    def __init__(self):
        self._lock = threading.Lock()
        # (deployment, pool_name) -> record
        self._pools: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def _records_for(self, spec: SubstrateSpec) -> Dict[str, Dict[str, Any]]:
        from kubeflow_tpu.topology.slices import list_slices

        known = set(list_slices())
        out: Dict[str, Dict[str, Any]] = {}
        for sp in spec.slice_pools:
            if not sp.name:
                raise SubstrateError("slicePools[].name is required")
            if sp.name in out:
                raise SubstrateError(
                    f"duplicate slice pool name {sp.name!r}")
            if sp.slice_type not in known:
                raise SubstrateError(
                    f"unknown slice_type {sp.slice_type!r} "
                    f"(catalog: {sorted(known)})")
            if sp.num_slices < 1:
                raise SubstrateError(
                    f"slice pool {sp.name}: numSlices must be >= 1")
            out[sp.name] = {"kind": "SlicePool", "name": sp.name,
                            "sliceType": sp.slice_type,
                            "numSlices": sp.num_slices}
        for np_ in spec.node_pools:
            if not np_.name:
                raise SubstrateError("nodePools[].name is required")
            if np_.name in out:
                raise SubstrateError(
                    f"pool name {np_.name!r} used by both a slice pool "
                    "and a node pool")
            if np_.count < 1:
                raise SubstrateError(
                    f"node pool {np_.name}: count must be >= 1")
            out[np_.name] = {"kind": "NodePool", "name": np_.name,
                             "machineType": np_.machine_type,
                             "count": np_.count}
        return out

    def validate_spec(self, spec: SubstrateSpec) -> None:
        self._records_for(spec)

    def ensure_pools(self, deployment: str,
                     spec: SubstrateSpec) -> List[str]:
        wanted = self._records_for(spec)
        with self._lock:
            current = {pool: rec for (dep, pool), rec in self._pools.items()
                       if dep == deployment}
            for pool, rec in wanted.items():
                if current.get(pool) != rec:
                    verb = "updated" if pool in current else "created"
                    self._pools[(deployment, pool)] = copy.deepcopy(rec)
                    log.info(f"substrate pool {verb}",
                             kv={"deployment": deployment, "pool": pool,
                                 "kind": rec["kind"]})
            for pool in set(current) - set(wanted):
                del self._pools[(deployment, pool)]
                log.info("substrate pool deleted (no longer in spec)",
                         kv={"deployment": deployment, "pool": pool})
        return sorted(wanted)

    def deprovision(self, deployment: str) -> List[str]:
        with self._lock:
            mine = [k for k in self._pools if k[0] == deployment]
            for k in mine:
                del self._pools[k]
        if mine:
            log.info("substrate deprovisioned",
                     kv={"deployment": deployment, "pools": len(mine)})
        return sorted(pool for _, pool in mine)

    def list_resources(self, deployment: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [copy.deepcopy(rec)
                    for (dep, _), rec in sorted(self._pools.items())
                    if dep == deployment]

    def reset(self) -> None:
        with self._lock:
            self._pools.clear()


# Provider registry: singletons, because substrate state outlives any one
# Platform engine instance (a cloud does too). Tests reset the fake.
PROVIDERS: Dict[str, SubstrateProvider] = {
    FakeSubstrateProvider.KIND: FakeSubstrateProvider(),
}


def get_provider(name: str) -> SubstrateProvider:
    if name not in PROVIDERS:
        raise SubstrateError(
            f"unknown substrate provider {name!r} "
            f"(registered: {sorted(PROVIDERS)})")
    return PROVIDERS[name]


def provision(deployment: str,
              spec: Optional[SubstrateSpec]) -> List[str]:
    """Apply(PLATFORM): run the provider half if the config asks for it.
    Returns provisioned pool names ([] when no substrate is requested)."""
    if spec is None or not spec.provider:
        return []
    return get_provider(spec.provider).ensure_pools(deployment, spec)


def deprovision_checked(deployment: str,
                        spec: Optional[SubstrateSpec]) -> List[str]:
    """Deprovision + leak check: anything the provider still tracks for
    the deployment afterwards is an error, not a warning."""
    if spec is None or not spec.provider:
        return []
    provider = get_provider(spec.provider)
    deleted = provider.deprovision(deployment)
    leaked = provider.list_resources(deployment)
    if leaked:
        raise SubstrateLeakError(
            f"deployment {deployment}: {len(leaked)} substrate resources "
            f"leaked after deprovision: "
            f"{[r['name'] for r in leaked]}")
    return deleted
