"""Cloud-substrate provisioning seam — the ``Apply(PLATFORM)`` half.

The reference's kfctl server doesn't just apply K8s manifests: it first
creates the cluster substrate through GCP Deployment Manager and tears it
down with a resource-leak check
(reference bootstrap/cmd/bootstrap/app/kfctlServer.go:219-296,
testing/kfctl/kfctl_delete_test.py:44-71). Here the substrate is TPU
slice pools + CPU node pools, created through a typed provider plugin
BEFORE the platform's k8s-level apply and reclaimed on deployment delete:

- ``SubstrateProvider``: the seam — ``ensure_pools`` (idempotent create/
  update), ``deprovision`` (delete everything the deployment owns),
  ``list_resources`` (the leak check's source of truth).
- ``FakeSubstrateProvider``: the in-env implementation (no cloud, zero
  egress) with real provider semantics: slice types validated against
  the topology catalog, spec-diffing updates, per-deployment ownership.
  A GCP/AWS implementation replaces the pool-record store with TPU API /
  EC2 calls — the seam's shape is the contract (same pattern as the
  profile controller's two IAM plugins, controllers/profile.py).
- Finalizer guard: ``Platform.apply_config`` adds SUBSTRATE_FINALIZER to
  the PlatformConfig; ``Platform.delete_config`` deprovisions, LEAK-CHECKS
  (raises if anything the provider still tracks survives), and only then
  removes the finalizer — delete leaves nothing, provably.
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.types import SubstrateSpec
from kubeflow_tpu.utils import get_logger

log = get_logger("substrate")

SUBSTRATE_FINALIZER = "substrate.tpu.kubeflow.org"


class SubstrateError(Exception):
    pass


class SubstrateLeakError(SubstrateError):
    """Deprovision left resources behind — the delete contract is broken
    (reference kfctl_delete_test.py:44-71 greps for leaked DM resources).
    """


class SubstrateProvider:
    """Provider seam. Implementations own (deployment, pool) -> resource
    lifecycles; all methods are synchronous and idempotent."""

    KIND = ""

    def ensure_pools(self, deployment: str,
                     spec: SubstrateSpec) -> List[str]:
        """Create/update every pool in ``spec``; delete pools the spec no
        longer lists (the deployment owns exactly its spec). Returns the
        pool names now live. Must be idempotent."""
        raise NotImplementedError

    def validate_spec(self, spec: SubstrateSpec) -> None:
        """Raise SubstrateError if ``spec`` could never provision — a
        DRY check with no side effects, so callers can validate a new
        substrate before tearing an old one down."""
        raise NotImplementedError

    def deprovision(self, deployment: str) -> List[str]:
        """Delete everything the deployment owns; returns what was
        deleted."""
        raise NotImplementedError

    def list_resources(self, deployment: str) -> List[Dict[str, Any]]:
        """Everything the provider still tracks for the deployment — the
        leak check reads this after deprovision."""
        raise NotImplementedError


def _spec_records(spec: SubstrateSpec) -> Dict[str, Dict[str, Any]]:
    """Validate a substrate spec and normalise it to pool records — the
    spec rules are provider-independent; only resource creation differs
    per provider."""
    from kubeflow_tpu.topology.slices import list_slices

    known = set(list_slices())
    out: Dict[str, Dict[str, Any]] = {}
    for sp in spec.slice_pools:
        if not sp.name:
            raise SubstrateError("slicePools[].name is required")
        if sp.name in out:
            raise SubstrateError(
                f"duplicate slice pool name {sp.name!r}")
        if sp.slice_type not in known:
            raise SubstrateError(
                f"unknown slice_type {sp.slice_type!r} "
                f"(catalog: {sorted(known)})")
        if sp.num_slices < 1:
            raise SubstrateError(
                f"slice pool {sp.name}: numSlices must be >= 1")
        out[sp.name] = {"kind": "SlicePool", "name": sp.name,
                        "sliceType": sp.slice_type,
                        "numSlices": sp.num_slices}
    for np_ in spec.node_pools:
        if not np_.name:
            raise SubstrateError("nodePools[].name is required")
        if np_.name in out:
            raise SubstrateError(
                f"pool name {np_.name!r} used by both a slice pool "
                "and a node pool")
        if np_.count < 1:
            raise SubstrateError(
                f"node pool {np_.name}: count must be >= 1")
        out[np_.name] = {"kind": "NodePool", "name": np_.name,
                         "machineType": np_.machine_type,
                         "count": np_.count}
    return out


class _MirrorStoreProvider(SubstrateProvider):
    """Shared provider skeleton: a (deployment, pool) -> record mirror of
    what exists cloud-side, with the diff/prune/ownership logic in one
    place. Subclasses implement ONLY resource creation/deletion
    (`_create_resource` / `_delete_resource`); the whole
    read-diff-mutate sequence holds the lock so concurrent ensure calls
    for one deployment cannot double-issue creates."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pools: Dict[Tuple[str, str], Dict[str, Any]] = {}

    # hooks -----------------------------------------------------------

    def _create_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        raise NotImplementedError

    def _delete_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        raise NotImplementedError

    # contract --------------------------------------------------------

    def validate_spec(self, spec: SubstrateSpec) -> None:
        _spec_records(spec)

    def ensure_pools(self, deployment: str,
                     spec: SubstrateSpec) -> List[str]:
        wanted = _spec_records(spec)
        with self._lock:
            current = {pool: rec for (dep, pool), rec in self._pools.items()
                       if dep == deployment}
            for pool, rec in wanted.items():
                if current.get(pool) == rec:
                    continue
                if pool in current:
                    # Pools are immutable cloud-side: recreate on change.
                    # Drop the mirror entry as soon as the delete lands so
                    # a failed create cannot leave a stale claim (retry
                    # would then re-issue the delete against nothing).
                    self._delete_resource(deployment, current[pool])
                    del self._pools[(deployment, pool)]
                self._create_resource(deployment, rec)
                self._pools[(deployment, pool)] = copy.deepcopy(rec)
                log.info("substrate pool ensured",
                         kv={"deployment": deployment, "pool": pool,
                             "kind": rec["kind"]})
            for pool in set(current) - set(wanted):
                self._delete_resource(deployment, current[pool])
                del self._pools[(deployment, pool)]
                log.info("substrate pool deleted (no longer in spec)",
                         kv={"deployment": deployment, "pool": pool})
        return sorted(wanted)

    def deprovision(self, deployment: str) -> List[str]:
        with self._lock:
            mine = {k: v for k, v in self._pools.items()
                    if k[0] == deployment}
            for (dep, pool), rec in sorted(mine.items()):
                self._delete_resource(dep, rec)
                del self._pools[(dep, pool)]
        if mine:
            log.info("substrate deprovisioned",
                     kv={"deployment": deployment, "pools": len(mine)})
        return sorted(pool for _, pool in mine)

    def list_resources(self, deployment: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [copy.deepcopy(rec)
                    for (dep, _), rec in sorted(self._pools.items())
                    if dep == deployment]

    def reset(self) -> None:
        with self._lock:
            self._pools.clear()


class FakeSubstrateProvider(_MirrorStoreProvider):
    """In-env provider: the mirror store IS the substrate."""

    KIND = "fake"

    def _create_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        pass

    def _delete_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        pass


class GcloudTpuProvider(_MirrorStoreProvider):
    """GCP implementation shaped around the real CLI surface: one
    `gcloud compute tpus tpu-vm create` per slice in a pool (the CLI
    creates one TPU VM per invocation) and GKE node pools under the
    deployment's cluster. The executor is injectable (same seam as the
    kubectl backend's subprocess boundary), so in this env — zero
    egress, no project — the provider is driven end-to-end against a
    recording executor while production swaps in subprocess.run. Proves
    the SubstrateProvider seam fits a second cloud the way the profile
    controller's AWS IRSA plugin proved the IAM seam.
    """

    KIND = "gcloud"

    def __init__(self, runner=None, project: str = "", zone: str = "",
                 cluster: str = "kubeflow-tpu",
                 runtime_version: str = "tpu-ubuntu2204-base"):
        super().__init__()
        self.project = project
        self.zone = zone
        self.cluster = cluster
        self.runtime_version = runtime_version
        self.runner = runner if runner is not None else self._no_runner

    @staticmethod
    def _no_runner(argv: List[str]) -> str:
        raise SubstrateError(
            "GcloudTpuProvider has no executor wired: construct it with "
            "runner=subprocess-backed callable (production) or a fake "
            "(tests)")

    def validate_spec(self, spec: SubstrateSpec) -> None:
        if self.runner is self._no_runner:
            # An unwired provider must fail at VALIDATION time: the
            # platform dry-validates a new substrate before tearing the
            # old one down, and "would fail on first command" must count
            # as invalid there.
            raise SubstrateError(
                "gcloud provider has no executor wired (construct with "
                "runner=...) — refusing to validate a spec it could "
                "never provision")
        super().validate_spec(spec)

    def _scope(self) -> List[str]:
        out = []
        if self.project:
            out += ["--project", self.project]
        if self.zone:
            out += ["--zone", self.zone]
        return out

    def _label(self, deployment: str) -> str:
        return f"kftpu-deployment={deployment}"

    def _slice_names(self, deployment: str, rec: Dict[str, Any]) -> List[str]:
        base = f"{deployment}-{rec['name']}"
        n = int(rec["numSlices"])
        return [base] if n == 1 else [f"{base}-{i}" for i in range(n)]

    def _create_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        if rec["kind"] == "SlicePool":
            for vm in self._slice_names(deployment, rec):
                self.runner([
                    "gcloud", "compute", "tpus", "tpu-vm", "create", vm,
                    "--accelerator-type", rec["sliceType"],
                    "--version", self.runtime_version,
                    "--labels", self._label(deployment),
                    *self._scope()])
        else:
            self.runner([
                "gcloud", "container", "node-pools", "create",
                f"{deployment}-{rec['name']}",
                "--cluster", self.cluster,
                "--machine-type", rec["machineType"],
                "--num-nodes", str(rec["count"]),
                "--node-labels", self._label(deployment),
                *self._scope()])

    def _delete_resource(self, deployment: str, rec: Dict[str, Any]) -> None:
        if rec["kind"] == "SlicePool":
            for vm in self._slice_names(deployment, rec):
                self.runner(["gcloud", "compute", "tpus", "tpu-vm",
                             "delete", vm, "--quiet", *self._scope()])
        else:
            self.runner(["gcloud", "container", "node-pools", "delete",
                         f"{deployment}-{rec['name']}",
                         "--cluster", self.cluster, "--quiet",
                         *self._scope()])


# Provider registry: singletons, because substrate state outlives any one
# Platform engine instance (a cloud does too). Tests reset the fake; the
# gcloud provider needs an executor wired before use (register a
# configured instance over this default).
PROVIDERS: Dict[str, SubstrateProvider] = {
    FakeSubstrateProvider.KIND: FakeSubstrateProvider(),
    GcloudTpuProvider.KIND: GcloudTpuProvider(),
}


def get_provider(name: str) -> SubstrateProvider:
    if name not in PROVIDERS:
        raise SubstrateError(
            f"unknown substrate provider {name!r} "
            f"(registered: {sorted(PROVIDERS)})")
    return PROVIDERS[name]


def provision(deployment: str,
              spec: Optional[SubstrateSpec]) -> List[str]:
    """Apply(PLATFORM): run the provider half if the config asks for it.
    Returns provisioned pool names ([] when no substrate is requested)."""
    if spec is None or not spec.provider:
        return []
    return get_provider(spec.provider).ensure_pools(deployment, spec)


def deprovision_checked(deployment: str,
                        spec: Optional[SubstrateSpec]) -> List[str]:
    """Deprovision + leak check: anything the provider still tracks for
    the deployment afterwards is an error, not a warning."""
    if spec is None or not spec.provider:
        return []
    provider = get_provider(spec.provider)
    deleted = provider.deprovision(deployment)
    leaked = provider.list_resources(deployment)
    if leaked:
        raise SubstrateLeakError(
            f"deployment {deployment}: {len(leaked)} substrate resources "
            f"leaked after deprovision: "
            f"{[r['name'] for r in leaked]}")
    return deleted
