"""Checkpoint catalog: named orbax checkpoints a notebook can spawn from.

The reference's Rok spawner variant lists storage snapshots and creates
notebooks from rok-token-authenticated snapshot URLs (reference
jupyter-web-app/backend/kubeflow_jupyter/rok/app.py:16-136). The
TPU-native analogue: TpuJobs write orbax checkpoints to
``spec.checkpoint_dir`` (train/checkpoint.py), and this catalog surfaces
every job-produced checkpoint in a namespace so the spawner can offer
"start from checkpoint X" — the notebook pod then gets
``KFTPU_RESTORE_DIR`` pointing at the snapshot.

Step discovery reads the orbax CheckpointManager layout directly (numeric
step subdirectories) — no orbax import in the control plane.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = ["latest_complete_step", "list_checkpoints", "resolve_checkpoint"]


def _torn_save(step_dir: str) -> bool:
    """True when a step directory is only PARTIALLY committed: a save
    torn by SIGKILL can leave the orbax in-progress marker *inside* the
    already-renamed step directory (the atomic-rename happened but the
    commit marker removal did not). Such a step must never be reported
    complete — a resized/restarted gang restoring it would read a torn
    tree. Markers recognized: any entry naming an orbax tmp/in-progress
    sentinel (``.orbax-checkpoint-tmp-*``, ``.orbax-in-progress``...)."""
    try:
        entries = os.listdir(step_dir)
    except OSError:
        return True     # unreadable = not restorable = not complete
    for e in entries:
        low = e.lower()
        if "orbax" in low and ("tmp" in low or "in-progress" in low
                               or "in_progress" in low):
            return True
    return False


def latest_complete_step(directory: str) -> Optional[int]:
    """Newest COMPLETE step in an orbax CheckpointManager directory (step
    subdirs are plain integers; in-progress saves normally carry a
    .orbax-* marker suffix and never parse as int). A step subdirectory
    whose in-progress marker lives *inside* it — a partially-committed
    save torn by SIGKILL — is skipped too (:func:`_torn_save`): the
    catalog only ever names steps a consumer can actually restore."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = [
        int(e) for e in entries
        if e.isdigit() and os.path.isdir(os.path.join(directory, e))
        and not _torn_save(os.path.join(directory, e))
    ]
    return max(steps) if steps else None


#: Backwards-compatible private alias (pre-elastic callers).
_latest_step = latest_complete_step


def list_checkpoints(api, namespace: str) -> List[Dict[str, Any]]:
    """Every TpuJob in the namespace whose checkpoint_dir holds at least
    one completed step. Sorted by name; entry names are the producing
    job's name (what the spawner shows and NotebookSpec.checkpoint
    stores)."""
    out = []
    for job in api.list("TpuJob", namespace=namespace, copy=False):
        d = job.spec.checkpoint_dir
        if not d:
            continue
        step = _latest_step(d)
        if step is None:
            continue
        out.append({
            "name": job.metadata.name,
            "dir": d,
            "latestStep": step,
            "sourceKind": "TpuJob",
            "model": job.spec.model,
        })
    return sorted(out, key=lambda e: e["name"])


def resolve_checkpoint(api, namespace: str,
                       name: str) -> Optional[Dict[str, Any]]:
    """The catalog entry for ``name``, or None (missing job, no
    checkpoint_dir, or no completed step yet). Direct lookup — this runs
    in the notebook controller's requeue path, so it must not scan every
    job's checkpoint directory."""
    job = api.try_get("TpuJob", name, namespace)
    if job is None or not job.spec.checkpoint_dir:
        return None
    step = _latest_step(job.spec.checkpoint_dir)
    if step is None:
        return None
    return {"name": job.metadata.name, "dir": job.spec.checkpoint_dir,
            "latestStep": step, "sourceKind": "TpuJob",
            "model": job.spec.model}
