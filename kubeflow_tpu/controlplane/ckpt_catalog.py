"""Checkpoint catalog: named orbax checkpoints a notebook can spawn from.

The reference's Rok spawner variant lists storage snapshots and creates
notebooks from rok-token-authenticated snapshot URLs (reference
jupyter-web-app/backend/kubeflow_jupyter/rok/app.py:16-136). The
TPU-native analogue: TpuJobs write orbax checkpoints to
``spec.checkpoint_dir`` (train/checkpoint.py), and this catalog surfaces
every job-produced checkpoint in a namespace so the spawner can offer
"start from checkpoint X" — the notebook pod then gets
``KFTPU_RESTORE_DIR`` pointing at the snapshot.

Step discovery reads the orbax CheckpointManager layout directly (numeric
step subdirectories) — no orbax import in the control plane.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

__all__ = ["list_checkpoints", "resolve_checkpoint"]


def _latest_step(directory: str) -> Optional[int]:
    """Newest COMPLETE step in an orbax CheckpointManager directory (step
    subdirs are plain integers; in-progress saves carry a .orbax-* marker
    suffix and never parse as int)."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = [int(e) for e in entries
             if e.isdigit() and os.path.isdir(os.path.join(directory, e))]
    return max(steps) if steps else None


def list_checkpoints(api, namespace: str) -> List[Dict[str, Any]]:
    """Every TpuJob in the namespace whose checkpoint_dir holds at least
    one completed step. Sorted by name; entry names are the producing
    job's name (what the spawner shows and NotebookSpec.checkpoint
    stores)."""
    out = []
    for job in api.list("TpuJob", namespace=namespace, copy=False):
        d = job.spec.checkpoint_dir
        if not d:
            continue
        step = _latest_step(d)
        if step is None:
            continue
        out.append({
            "name": job.metadata.name,
            "dir": d,
            "latestStep": step,
            "sourceKind": "TpuJob",
            "model": job.spec.model,
        })
    return sorted(out, key=lambda e: e["name"])


def resolve_checkpoint(api, namespace: str,
                       name: str) -> Optional[Dict[str, Any]]:
    """The catalog entry for ``name``, or None (missing job, no
    checkpoint_dir, or no completed step yet). Direct lookup — this runs
    in the notebook controller's requeue path, so it must not scan every
    job's checkpoint directory."""
    job = api.try_get("TpuJob", name, namespace)
    if job is None or not job.spec.checkpoint_dir:
        return None
    step = _latest_step(job.spec.checkpoint_dir)
    if step is None:
        return None
    return {"name": job.metadata.name, "dir": job.spec.checkpoint_dir,
            "latestStep": step, "sourceKind": "TpuJob",
            "model": job.spec.model}
