"""Event recorder: CR-attached events as UX, the reference's pattern of
re-emitting pod events onto owning CRs (notebook_controller.go:86-105) and
JWA folding events into status (jupyter .../utils.py:262-335)."""

from __future__ import annotations

import uuid
from typing import Any

from kubeflow_tpu.controlplane.api.core import Event
from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.runtime.apiserver import InMemoryApiServer


class EventRecorder:
    def __init__(self, api: InMemoryApiServer, component: str):
        self.api = api
        self.component = component

    def event(
        self, obj: Any, type_: str, reason: str, message: str
    ) -> Event:
        ns = obj.metadata.namespace or "default"
        ev = Event(
            metadata=ObjectMeta(
                name=f"{obj.metadata.name}.{uuid.uuid4().hex[:10]}",
                namespace=ns,
                labels={"component": self.component},
            ),
            involved_kind=obj.kind,
            involved_name=obj.metadata.name,
            involved_namespace=obj.metadata.namespace,
            type=type_,
            reason=reason,
            message=message,
        )
        return self.api.create(ev)

    def events_for(self, obj: Any):
        return [
            e for e in self.api.list("Event", namespace=obj.metadata.namespace,
                                     copy=False)
            if e.involved_kind == obj.kind and e.involved_name == obj.metadata.name
        ]
