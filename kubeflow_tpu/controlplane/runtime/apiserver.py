"""In-memory API server: typed object store with watch, optimistic
concurrency, finalizers and owner-reference garbage collection.

This is the platform's envtest analogue — the reference tests controllers
against a real etcd+apiserver spun up per suite (components/
profile-controller/controllers/suite_test.go:50-72); we provide the same
semantics in-process so every controller test runs in milliseconds, and the
store's interface is the seam where a real K8s client is substituted in a
cluster deployment.

Semantics implemented (the subset the reference's controllers rely on):
- resourceVersion bump on every write; update with a stale version raises
  ConflictError (optimistic concurrency, the retry-on-conflict loops in
  profile_controller.go:150-154).
- delete with finalizers present only sets deletionTimestamp; the object
  goes away when the last finalizer is removed (plugin teardown,
  profile_controller.go Reconcile finalizer path).
- ownerReferences cascade: deleting an owner deletes its dependents
  (how STS->pods and job->pods cleanup behaves for the reference).
- label-selector list; namespaced and cluster-scoped kinds.
- watch: per-subscriber queues receiving ADDED/MODIFIED/DELETED events.
"""

from __future__ import annotations

import copy
import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.meta import fresh_identity

CLUSTER_SCOPED = {"Namespace", "Profile", "PlatformConfig"}


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    pass


@dataclasses.dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED
    object: Any


Key = Tuple[str, str, str]  # (kind, namespace, name)


def _key(obj: Any) -> Key:
    kind = obj.kind
    ns = "" if kind in CLUSTER_SCOPED else obj.metadata.namespace
    return (kind, ns, obj.metadata.name)


class InMemoryApiServer:
    def __init__(self) -> None:
        self._objects: Dict[Key, Any] = {}
        self._rv = 0
        self._lock = threading.RLock()
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        # Admission mutators run on create (the PodDefault webhook seam,
        # admission-webhook/main.go:389-470).
        self._mutators: List[Callable[[Any], Any]] = []

    # ----------------- helpers -----------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(self, event: WatchEvent) -> None:
        for kind, q in list(self._watchers):
            if kind is None or kind == event.object.kind:
                q.put(event)

    def register_mutator(self, fn: Callable[[Any], Any]) -> None:
        with self._lock:
            self._mutators.append(fn)

    # ----------------- CRUD -----------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            obj = copy.deepcopy(obj)
            if not obj.metadata.name:
                raise ApiError(f"{obj.kind}: metadata.name required")
            if obj.kind not in CLUSTER_SCOPED and not obj.metadata.namespace:
                raise ApiError(f"{obj.kind}/{obj.metadata.name}: namespace required")
            key = _key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            for m in self._mutators:
                out = m(obj)
                if out is not None:
                    obj = out
            fresh_identity(obj.metadata)
            obj.metadata.resource_version = self._next_rv()
            obj.metadata.generation = 1
            self._objects[key] = obj
            out = copy.deepcopy(obj)
        self._notify(WatchEvent("ADDED", copy.deepcopy(obj)))
        return out

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        with self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            obj = self._objects.get((kind, ns, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[Any]:
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def update(self, obj: Any) -> Any:
        with self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            obj = copy.deepcopy(obj)
            # Identity fields are server-owned.
            obj.metadata.uid = cur.metadata.uid
            obj.metadata.creation_timestamp = cur.metadata.creation_timestamp
            obj.metadata.resource_version = self._next_rv()
            if self._spec_changed(cur, obj):
                obj.metadata.generation = cur.metadata.generation + 1
            self._objects[key] = obj

            if (
                obj.metadata.deletion_timestamp is not None
                and not obj.metadata.finalizers
            ):
                del self._objects[key]
                out = copy.deepcopy(obj)
                self._notify(WatchEvent("DELETED", copy.deepcopy(obj)))
                self._cascade_delete(obj)
                return out
            out = copy.deepcopy(obj)
        self._notify(WatchEvent("MODIFIED", copy.deepcopy(obj)))
        return out

    @staticmethod
    def _spec_changed(a: Any, b: Any) -> bool:
        sa = getattr(a, "spec", None)
        sb = getattr(b, "spec", None)
        return sa != sb

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            key = (kind, ns, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur = copy.deepcopy(cur)
                    cur.metadata.deletion_timestamp = time.time()
                    cur.metadata.resource_version = self._next_rv()
                    self._objects[key] = cur
                    self._notify(WatchEvent("MODIFIED", copy.deepcopy(cur)))
                return
            del self._objects[key]
            obj = cur
        self._notify(WatchEvent("DELETED", copy.deepcopy(obj)))
        self._cascade_delete(obj)

    def _cascade_delete(self, owner: Any) -> None:
        """Delete dependents referencing the owner's uid."""
        uid = owner.metadata.uid
        with self._lock:
            dependents = [
                o for o in self._objects.values()
                if any(r.uid == uid for r in o.metadata.owner_references)
            ]
        for dep in dependents:
            try:
                self.delete(dep.kind, dep.metadata.name, dep.metadata.namespace)
            except NotFoundError:
                pass

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objects.items():
                if k != kind:
                    continue
                if namespace is not None and kind not in CLUSTER_SCOPED \
                        and ns != namespace:
                    continue
                if label_selector and not all(
                    obj.metadata.labels.get(lk) == lv
                    for lk, lv in label_selector.items()
                ):
                    continue
                out.append(copy.deepcopy(obj))
            return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))

    # ----------------- status + finalizer conveniences -----------------

    def update_status(self, obj: Any) -> Any:
        """Update ONLY the status subresource (concurrent spec writes win)."""
        with self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            new = copy.deepcopy(cur)
            new.status = copy.deepcopy(obj.status)
            new.metadata.resource_version = self._next_rv()
            self._objects[key] = new
            out = copy.deepcopy(new)
        self._notify(WatchEvent("MODIFIED", copy.deepcopy(new)))
        return out

    # ----------------- watch -----------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            # Replay current state so late watchers converge (informer-style).
            for obj in self._objects.values():
                if kind is None or obj.kind == kind:
                    q.put(WatchEvent("ADDED", copy.deepcopy(obj)))
            self._watchers.append((kind, q))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]
