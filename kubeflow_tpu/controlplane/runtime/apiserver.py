"""In-memory API server: typed object store with watch, optimistic
concurrency, finalizers and owner-reference garbage collection.

This is the platform's envtest analogue — the reference tests controllers
against a real etcd+apiserver spun up per suite (components/
profile-controller/controllers/suite_test.go:50-72); we provide the same
semantics in-process so every controller test runs in milliseconds, and the
store's interface is the seam where a real K8s client is substituted in a
cluster deployment.

Semantics implemented (the subset the reference's controllers rely on):
- resourceVersion bump on every write; update with a stale version raises
  ConflictError (optimistic concurrency, the retry-on-conflict loops in
  profile_controller.go:150-154).
- delete with finalizers present only sets deletionTimestamp; the object
  goes away when the last finalizer is removed (plugin teardown,
  profile_controller.go Reconcile finalizer path).
- ownerReferences cascade: deleting an owner deletes its dependents
  (how STS->pods and job->pods cleanup behaves for the reference).
- label-selector list; namespaced and cluster-scoped kinds.
- watch: per-subscriber queues receiving ADDED/MODIFIED/DELETED events.

Scaling model (docs/controlplane-perf.md): the store keeps **canonical
immutable snapshots**. Every write deep-copies the inbound object once and
*replaces* the stored snapshot — a snapshot, once stored, is never edited
in place. That invariant is what makes the read path cheap:

- ``get``/``try_get``/``list`` default to ``copy=True`` (a private,
  mutate-then-update-able copy — the read-modify-write idiom every
  controller write loop uses), but read-only callers pass ``copy=False``
  and receive the shared snapshot with **zero** copying.
- ``list`` resolves through per-kind / per-(kind, namespace) secondary
  indexes, so its cost — and, with ``copy=True``, its copy count — scales
  with the number of *matching* objects, never with store size.
- watch events share one event object (and the stored snapshot) across
  all subscribers; late-watcher replay reuses the stored snapshots too.
- ``_cascade_delete`` resolves dependents through an owner-uid index,
  breadth-first, instead of re-scanning the whole store per level.

Zero-copy results are read-only by contract (exactly client-go's shared
informer cache contract). Read-path copies are tallied per verb in
``self.copied`` and exported as
``kftpu_apiserver_objects_copied_total{verb}`` so benches and the CI
``cp-bench-smoke`` stage can assert the O(matches) property by counting,
not timing.

Scale semantics (ISSUE 6 — the sharded control plane's API contract):

- ``list(limit=, continue_=)`` paginates: the first page pins the sorted
  query result as a **snapshot** at the current resource version and
  returns an opaque continue token; every subsequent page walks that
  snapshot, so a ``limit`` walk enumerates EXACTLY the unpaginated list
  as of the walk's start, regardless of concurrent writes (the etcd
  paginate-at-one-revision contract). Abandoned walks are LRU-evicted;
  continuing one raises :class:`ContinueExpiredError` (K8s' 410 Gone).
- ``watch(bookmarks=True)`` opts a subscription into **BOOKMARK** events:
  one immediately after replay carrying the snapshot resource version,
  then periodically as writes advance the store. A consumer that persists
  the last bookmark rv can resubscribe with ``watch(resume_rv=rv)`` and
  receive only the events it missed (served from a bounded event log)
  instead of an O(store) ADDED replay — the restart path sharded managers
  and ``CachedReader`` use. Replay work is tallied in ``self.replayed``
  per mode (``full`` / ``resume``), counts again, so resync tests gate on
  numbers rather than timing.
- ``set_journal(fn)`` installs a write-ahead hook called under the store
  lock for every committed write, in commit order, *before* the watch
  notify — the seam ``controlplane/wal.py`` uses to make a shard's state
  replayable after a crash.
"""

from __future__ import annotations

import base64
import collections
import dataclasses
import json
import queue
import threading
import time
from copy import deepcopy
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.meta import fresh_identity
from kubeflow_tpu.utils import locktrace
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry
from kubeflow_tpu.utils.tracing import SpanContext, Tracer, global_tracer

CLUSTER_SCOPED = {"Namespace", "Profile", "PlatformConfig"}


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    pass


class ContinueExpiredError(ApiError):
    """The continue token's pinned snapshot was evicted (too many
    concurrent walks, or the walk was abandoned and later resumed) — the
    K8s 410 Gone analogue. Restart the walk from the first page."""


@dataclasses.dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED | BOOKMARK | RELIST
    object: Any        # None for BOOKMARK/RELIST events
    # Observability stamps, set at notify time (zero-cost to consumers
    # that ignore them): when the event was enqueued (monotonic — the
    # watch-delivery-lag measurement point) and the span context of the
    # write that produced it (the write-RV → reconcile trace link).
    ts_mono: float = 0.0
    span_ctx: Optional[SpanContext] = None
    # Store resource version as of this event (stamped under the store
    # lock). BOOKMARK events carry ONLY this: "you have seen everything
    # up to rv" — the resume point for watch(resume_rv=...).
    rv: int = 0


@dataclasses.dataclass
class ListPage:
    """One page of a paginated ``list``: the items, the opaque token for
    the next page (``""`` when the walk is complete), and the resource
    version the whole walk is pinned to."""

    items: List[Any]
    continue_: str
    resource_version: int


def _encode_continue(snap_id: int, offset: int, rv: int) -> str:
    payload = json.dumps({"id": snap_id, "off": offset, "rv": rv},
                         separators=(",", ":"))
    return base64.urlsafe_b64encode(payload.encode()).decode()


def _decode_continue(token: str) -> Dict[str, int]:
    try:
        data = json.loads(base64.urlsafe_b64decode(token.encode()).decode())
        return {"id": int(data["id"]), "off": int(data["off"]),
                "rv": int(data["rv"])}
    except Exception:
        raise ApiError(f"malformed continue token {token!r}") from None


Key = Tuple[str, str, str]  # (kind, namespace, name)


def _key(obj: Any) -> Key:
    kind = obj.kind
    ns = "" if kind in CLUSTER_SCOPED else obj.metadata.namespace
    return (kind, ns, obj.metadata.name)


def match_labels(obj: Any, selector: Optional[Dict[str, str]]) -> bool:
    """The list() label-selector predicate, shared with CachedReader so the
    informer cache cannot drift from the server's matching semantics."""
    if not selector:
        return True
    labels = obj.metadata.labels
    return all(labels.get(k) == v for k, v in selector.items())


def _sorted_objs(objs: List[Any]) -> List[Any]:
    return sorted(objs, key=lambda o: (o.metadata.namespace, o.metadata.name))


def index_put(by_kind: Dict[str, Dict[Key, Any]],
              by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
              key: Key, obj: Any) -> None:
    """Insert into the kind / (kind, namespace) index pair. Shared with
    CachedReader so the two index implementations cannot drift."""
    by_kind.setdefault(key[0], {})[key] = obj
    by_kind_ns.setdefault(key[:2], {})[key] = obj


def list_bucket(by_kind: Dict[str, Dict[Key, Any]],
                by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
                kind: str, namespace: Optional[str],
                label_selector: Optional[Dict[str, str]]) -> List[Any]:
    """Resolve a list() query against the index pair: pick the bucket,
    apply the selector. One implementation shared by the server and the
    informer cache so their answers cannot drift. Callers hold their own
    lock and sort/copy the result themselves."""
    if namespace is None or kind in CLUSTER_SCOPED:
        bucket = by_kind.get(kind, {})
    else:
        bucket = by_kind_ns.get((kind, namespace), {})
    return [obj for obj in bucket.values()
            if match_labels(obj, label_selector)]


def index_drop(by_kind: Dict[str, Dict[Key, Any]],
               by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
               key: Key) -> None:
    """Remove from the index pair, pruning buckets that empty out (a
    long-lived store/cache must not accumulate one dead dict per kind or
    namespace ever seen)."""
    for mapping, mkey in ((by_kind, key[0]), (by_kind_ns, key[:2])):
        bucket = mapping.get(mkey)
        if bucket is not None:
            bucket.pop(key, None)
            if not bucket:
                del mapping[mkey]


#: Span-name table: f-string per call showed up in sweep profiles.
_VERB_SPAN_NAMES = {
    v: f"apiserver.{v}"
    for v in ("create", "get", "update", "update_status", "delete", "list")
}


class _VerbSpan:
    """Hand-rolled context manager for the API verb hot path: one
    tracer span + one latency observation, without the two nested
    generator context managers the idiomatic form costs per call
    (profiled: ~3% of a whole control-plane sweep)."""

    __slots__ = ("api", "verb", "span")

    def __init__(self, api: "InMemoryApiServer", verb: str, kind: str,
                 name: str, namespace: str):
        self.api = api
        self.verb = verb
        self.span = api.tracer.start(
            _VERB_SPAN_NAMES.get(verb, f"apiserver.{verb}"),
            attrs={"verb": verb, "kind": kind, "name": name,
                   "namespace": namespace},
        )

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        self.api.tracer.finish(self.span)
        self.api.metrics_latency.observe(self.span.duration_s,
                                         verb=self.verb)
        return False


class InMemoryApiServer:
    #: Pinned pagination snapshots kept at once; the least recently started
    #: walk is evicted first (its continue token then raises
    #: ContinueExpiredError). Completed walks free their snapshot eagerly.
    MAX_PAGE_SNAPSHOTS = 64

    def __init__(self, registry: MetricsRegistry = global_registry,
                 tracer: Tracer = global_tracer, *,
                 bookmark_interval: int = 50,
                 event_log_size: int = 4096) -> None:
        self._objects: Dict[Key, Any] = {}
        # Secondary indexes (all under self._lock, all holding the same
        # snapshot references as self._objects — replaced together on
        # every write):
        self._by_kind: Dict[str, Dict[Key, Any]] = {}
        self._by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]] = {}
        self._by_owner: Dict[str, Dict[Key, Any]] = {}   # owner uid -> deps
        self._rv = 0
        # Reentrant (watch-notify paths re-enter reads) and built through
        # the locktrace factory so the chaos soaks' lock-order detector
        # sees the store lock's position in every ordering edge.
        self._lock = locktrace.rlock("apiserver.store")
        # (kind filter, queue, wants_bookmarks)
        self._watchers: List[
            Tuple[Optional[str], "queue.Queue[WatchEvent]", bool]
        ] = []
        # Admission mutators run on create (the PodDefault webhook seam,
        # admission-webhook/main.go:389-470).
        self._mutators: List[Callable[[Any], Any]] = []
        # Bounded recent-event log (shared event objects — no copies):
        # what watch(resume_rv=...) serves its delta replay from. rvs in
        # the log are contiguous (every rv bump emits exactly one event).
        self._event_log: "collections.deque[WatchEvent]" = collections.deque(
            maxlen=max(1, int(event_log_size)))
        # Periodic BOOKMARK cadence, counted in writes since the last one.
        self.bookmark_interval = max(1, int(bookmark_interval))
        self._writes_since_bookmark = 0
        # Pinned pagination snapshots: id -> (rv, sorted shared snapshots).
        self._page_snapshots: "collections.OrderedDict[int, Tuple[int, List[Any]]]" = \
            collections.OrderedDict()
        self._page_seq = 0
        # Write-ahead journal hook (controlplane/wal.py): called under the
        # store lock, in commit order, before the watch notify.
        self._journal: Optional[Callable[[str, Any, int], None]] = None
        # Read-path deepcopy tally, per verb ("get"/"list"). Deterministic
        # (a pure function of the call sequence), so benches and CI gate on
        # counts instead of wall-clock.
        self.copied: Dict[str, int] = {}
        # Watch replay tally, per mode: "full" counts objects replayed by
        # O(bucket) ADDED replay, "resume" counts delta events served from
        # the event log. Deterministic, so resync tests gate on counts.
        self.replayed: Dict[str, int] = {}
        self.metrics_copied = registry.counter(
            "kftpu_apiserver_objects_copied_total",
            "Objects deep-copied on the API server read path",
            labels=("verb",),
        )
        self.metrics_replayed = registry.counter(
            "kftpu_apiserver_watch_replayed_total",
            "Objects/events replayed to new watch subscriptions",
            labels=("mode",),
        )
        self.tracer = tracer
        self.metrics_latency = registry.histogram(
            "kftpu_apiserver_request_duration_seconds",
            "API server verb latency",
            labels=("verb",),
        )

    # ----------------- helpers -----------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _count_copies(self, verb: str, n: int) -> None:
        if n <= 0:
            return
        self.copied[verb] = self.copied.get(verb, 0) + n
        self.metrics_copied.inc(n, verb=verb)

    def copied_total(self) -> int:
        return sum(self.copied.values())

    def _count_replayed(self, mode: str, n: int) -> None:
        if n <= 0:
            return
        self.replayed[mode] = self.replayed.get(mode, 0) + n
        self.metrics_replayed.inc(n, mode=mode)

    def set_journal(self, fn: Optional[Callable[[str, Any, int], None]]) -> None:
        """Install the write-ahead hook: ``fn(op, payload, rv)`` with
        ``op`` in {"put", "del"}; payload is the stored snapshot for puts
        and the ``(kind, namespace, name)`` key for dels. Called under the
        store lock in commit order, BEFORE the watch notify — a record is
        durable before its event is visible."""
        with self._lock:
            self._journal = fn

    def _journal_put(self, obj: Any) -> None:
        if self._journal is not None:
            self._journal("put", obj, self._rv)

    def _journal_del(self, key: Key) -> None:
        if self._journal is not None:
            self._journal("del", key, self._rv)

    def _verb_span(self, verb: str, kind: str, name: str = "",
                   namespace: str = "") -> "_VerbSpan":
        """One span + latency-histogram observation per API verb call
        (observed on success AND failure — an erroring verb still took
        time). Write verbs additionally set the resulting ``rv`` attr
        inside the verb body (the write-RV the reconcile trace links
        back to)."""
        return _VerbSpan(self, verb, kind, name, namespace)

    def _index_add(self, key: Key, obj: Any) -> None:
        index_put(self._by_kind, self._by_kind_ns, key, obj)
        for ref in obj.metadata.owner_references:
            if ref.uid:
                self._by_owner.setdefault(ref.uid, {})[key] = obj

    def _index_remove(self, key: Key, obj: Any) -> None:
        index_drop(self._by_kind, self._by_kind_ns, key)
        for ref in obj.metadata.owner_references:
            bucket = self._by_owner.get(ref.uid)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self._by_owner[ref.uid]

    def _store(self, key: Key, obj: Any) -> None:
        """Replace (never edit) the stored snapshot, keeping every index in
        step — owner references may have changed on update."""
        old = self._objects.get(key)
        if old is not None:
            self._index_remove(key, old)
        self._objects[key] = obj
        self._index_add(key, obj)

    def _remove(self, key: Key) -> Any:
        obj = self._objects.pop(key)
        self._index_remove(key, obj)
        return obj

    def _notify(self, event: WatchEvent) -> None:
        # Stamp delivery time + the writing span's context on the shared
        # event: the reconciler measures watch-delivery lag against
        # ts_mono and links its reconcile span to span_ctx (one trace
        # from write to status update).
        event.ts_mono = time.monotonic()
        event.span_ctx = self.tracer.current_context()
        event.rv = self._rv
        self._event_log.append(event)
        # ONE event object shared by every subscriber: the payload is the
        # stored snapshot, which is immutable by contract, so per-watcher
        # deep copies bought nothing but O(watchers) deepcopy per write.
        # Always called with self._lock held, so delivery order == write
        # order — the invariant last-wins consumers (CachedReader) rely on;
        # notifying outside the lock let two racing writers enqueue their
        # events in the wrong order and wedge a cache stale forever.
        for kind, q, _bm in list(self._watchers):
            if kind is None or kind == event.object.kind:
                q.put(event)
        # Periodic BOOKMARK to opted-in subscribers: "you have seen
        # everything through rv" — what lets a restarted consumer resync
        # with watch(resume_rv=rv) instead of an O(store) relist.
        self._writes_since_bookmark += 1
        if self._writes_since_bookmark >= self.bookmark_interval:
            self._emit_bookmark_locked()

    def _emit_bookmark_locked(self) -> None:
        self._writes_since_bookmark = 0
        bm = WatchEvent("BOOKMARK", None, ts_mono=time.monotonic(),
                        rv=self._rv)
        for _kind, q, bookmarks in list(self._watchers):
            if bookmarks:
                q.put(bm)

    def register_mutator(self, fn: Callable[[Any], Any]) -> None:
        with self._lock:
            self._mutators.append(fn)

    def load_snapshot(self, obj: Any) -> None:
        """Restore a persisted object verbatim: identity fields kept, no
        resourceVersion bump, no watch events, indexes maintained — the
        Platform.save/load seam. (Writing into ``_objects`` directly would
        leave the secondary indexes empty.)"""
        with self._lock:
            self._store(_key(obj), obj)

    def drop_snapshot(self, kind: str, name: str, namespace: str = "") -> None:
        """Remove a restored object verbatim: no events, no finalizer
        semantics, no cascade — the WAL ``del``-record replay seam
        (``delete()`` would re-run lifecycle logic that already ran before
        the crash). Missing objects are ignored."""
        with self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            key = (kind, ns, name)
            if key in self._objects:
                self._remove(key)

    # ----------------- CRUD -----------------

    def create(self, obj: Any) -> Any:
        with self._verb_span("create", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            obj = deepcopy(obj)
            if not obj.metadata.name:
                raise ApiError(f"{obj.kind}: metadata.name required")
            if obj.kind not in CLUSTER_SCOPED and not obj.metadata.namespace:
                raise ApiError(f"{obj.kind}/{obj.metadata.name}: namespace required")
            key = _key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            for m in self._mutators:
                out = m(obj)
                if out is not None:
                    obj = out
            fresh_identity(obj.metadata)
            obj.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = obj.metadata.resource_version
            obj.metadata.generation = 1
            self._store(key, obj)
            self._journal_put(obj)
            out = deepcopy(obj)
            self._notify(WatchEvent("ADDED", obj))
        return out

    def get(self, kind: str, name: str, namespace: str = "", *,
            copy: bool = True) -> Any:
        """``copy=True`` (default) returns a private mutate-then-update-able
        copy; ``copy=False`` returns the shared snapshot (read-only by
        contract — never mutate it)."""
        with self._verb_span("get", kind, name, namespace), self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            obj = self._objects.get((kind, ns, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if not copy:
                return obj
            self._count_copies("get", 1)
            return deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "", *,
                copy: bool = True) -> Optional[Any]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def update(self, obj: Any) -> Any:
        with self._verb_span("update", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            obj = deepcopy(obj)
            # Identity fields are server-owned.
            obj.metadata.uid = cur.metadata.uid
            obj.metadata.creation_timestamp = cur.metadata.creation_timestamp
            obj.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = obj.metadata.resource_version
            if self._spec_changed(cur, obj):
                obj.metadata.generation = cur.metadata.generation + 1
            removed = (
                obj.metadata.deletion_timestamp is not None
                and not obj.metadata.finalizers
            )
            if removed:
                # Last finalizer cleared: the update completes the delete —
                # don't pay a _store index add just to tear it down again.
                self._remove(key)
                self._journal_del(key)
                self._notify(WatchEvent("DELETED", obj))
            else:
                self._store(key, obj)
                self._journal_put(obj)
                self._notify(WatchEvent("MODIFIED", obj))
            out = deepcopy(obj)
        if removed:
            # Cascade OUTSIDE the lock (like delete()): a finalizer clear on
            # an owner must not stall all API traffic for the whole
            # dependent-tree teardown.
            self._cascade_delete(obj)
        return out

    @staticmethod
    def _spec_changed(a: Any, b: Any) -> bool:
        sa = getattr(a, "spec", None)
        sb = getattr(b, "spec", None)
        return sa != sb

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._verb_span("delete", kind, name, namespace):
            removed = self._delete_one(kind, name, namespace)
            if removed is not None:
                self._cascade_delete(removed)

    def _delete_one(self, kind: str, name: str, namespace: str) -> Optional[Any]:
        """Delete without cascading; returns the removed object, or None when
        finalizers only marked it (deletionTimestamp set, object retained)."""
        with self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            key = (kind, ns, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur = deepcopy(cur)
                    cur.metadata.deletion_timestamp = time.time()
                    cur.metadata.resource_version = self._next_rv()
                    self._store(key, cur)
                    self._journal_put(cur)
                    self._notify(WatchEvent("MODIFIED", cur))
                return None
            self._remove(key)
            # A hard delete consumes a resource version of its own (the
            # etcd convention): the DELETED event then has a unique rv, so
            # a resume_rv replay can never skip past a deletion that
            # shares its predecessor's version.
            self._next_rv()
            self._journal_del(key)
            self._notify(WatchEvent("DELETED", cur))
            return cur

    def _cascade_delete(self, owner: Any) -> None:
        """Delete dependents referencing the owner's uid, breadth-first via
        the owner-uid index — the old implementation re-scanned the whole
        store once per dependency *level*."""
        pending: "collections.deque[str]" = collections.deque(
            [owner.metadata.uid]
        )
        while pending:
            uid = pending.popleft()
            with self._lock:
                deps = list(self._by_owner.get(uid, {}).values())
            for dep in deps:
                try:
                    removed = self._delete_one(
                        dep.kind, dep.metadata.name, dep.metadata.namespace
                    )
                except NotFoundError:
                    continue
                if removed is not None:
                    pending.append(removed.metadata.uid)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        *,
        copy: bool = True,
        limit: Optional[int] = None,
        continue_: Optional[str] = None,
    ):
        """Index-resolved list: touches only the (kind) or (kind, namespace)
        bucket, so cost is O(bucket) and copy count (``copy=True``) is
        O(matches) — never O(store). ``copy=False`` returns the shared
        snapshots (read-only by contract).

        With ``limit`` (and then ``continue_``) the result is a
        :class:`ListPage` instead of a plain list: the first page pins the
        sorted result as a snapshot at the current resource version, and
        the opaque token walks that snapshot — the whole walk enumerates
        exactly the store as of its first page, no matter what writes land
        in between. Copy counts are O(page) per call."""
        with self._verb_span("list", kind, namespace=namespace or ""):
            if limit is not None or continue_ is not None:
                return self._list_page(kind, namespace, label_selector,
                                       copy=copy, limit=limit,
                                       continue_=continue_)
            with self._lock:
                out = list_bucket(self._by_kind, self._by_kind_ns,
                                  kind, namespace, label_selector)
                if copy:
                    self._count_copies("list", len(out))
            if copy:
                # Snapshots are immutable once stored, so the copies happen
                # OUTSIDE the lock — a big copy=True list must not stall
                # every concurrent writer for the duration of the deepcopy
                # loop.
                out = [deepcopy(o) for o in out]
            return _sorted_objs(out)

    def _list_page(
        self,
        kind: str,
        namespace: Optional[str],
        label_selector: Optional[Dict[str, str]],
        *,
        copy: bool,
        limit: Optional[int],
        continue_: Optional[str],
    ) -> ListPage:
        if limit is not None and limit < 1:
            # Validated on EVERY page: a continuation with limit<=0 would
            # return an empty page whose token never advances, spinning a
            # standard `while page.continue_` walk forever.
            raise ApiError(f"list limit must be >= 1, got {limit}")
        if continue_:
            tok = _decode_continue(continue_)
            with self._lock:
                snap = self._page_snapshots.get(tok["id"])
                if snap is None or snap[0] != tok["rv"]:
                    raise ContinueExpiredError(
                        f"continue token for {kind} expired "
                        "(snapshot evicted) — restart the walk"
                    )
                # Touch the walk so eviction is genuinely LRU: without
                # this, an ACTIVE walk ages by start time and gets
                # evicted under newer walks mid-pagination.
                self._page_snapshots.move_to_end(tok["id"])
                rv, objs = snap
            offset = tok["off"]
            snap_id = tok["id"]
        else:
            if limit is None:
                raise ApiError("paginated list requires a limit")
            with self._lock:
                rv = self._rv
                objs = _sorted_objs(list_bucket(
                    self._by_kind, self._by_kind_ns,
                    kind, namespace, label_selector,
                ))
                self._page_seq += 1
                snap_id = self._page_seq
                # The snapshot holds SHARED references to immutable stored
                # snapshots — pinning a walk costs one list of pointers,
                # never a copy, and keeps deleted objects alive only until
                # the walk finishes or is evicted.
                self._page_snapshots[snap_id] = (rv, objs)
                while len(self._page_snapshots) > self.MAX_PAGE_SNAPSHOTS:
                    self._page_snapshots.popitem(last=False)
            offset = 0
        end = len(objs) if limit is None else min(offset + int(limit),
                                                  len(objs))
        page = objs[offset:end]
        if end >= len(objs):
            token = ""
            with self._lock:
                self._page_snapshots.pop(snap_id, None)
        else:
            token = _encode_continue(snap_id, end, rv)
        if copy:
            with self._lock:
                self._count_copies("list", len(page))
            page = [deepcopy(o) for o in page]
        return ListPage(items=page, continue_=token, resource_version=rv)

    def list_all(self) -> List[Any]:
        """Every stored snapshot, all kinds, shared zero-copy (read-only by
        contract) — the store-wide enumeration benches and state
        fingerprints use instead of reaching into ``_objects``."""
        with self._lock:
            return list(self._objects.values())

    # ----------------- status + finalizer conveniences -----------------

    def update_status(self, obj: Any) -> Any:
        """Update ONLY the status subresource (concurrent spec writes win)."""
        with self._verb_span("update_status", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            new = deepcopy(cur)
            new.status = deepcopy(obj.status)
            new.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = new.metadata.resource_version
            self._store(key, new)
            self._journal_put(new)
            out = deepcopy(new)
            self._notify(WatchEvent("MODIFIED", new))
        return out

    # ----------------- watch -----------------

    def watch(self, kind: Optional[str] = None, *,
              resume_rv: Optional[int] = None,
              bookmarks: bool = False) -> "queue.Queue[WatchEvent]":
        """Subscribe to events for ``kind`` (None = all kinds).

        ``bookmarks=True`` opts in to BOOKMARK events: one immediately
        after replay carrying the snapshot resource version, then
        periodically as writes land (consumers must skip events whose
        ``object`` is None). ``resume_rv`` (implies bookmarks) resumes
        from a previously bookmarked version: when the bounded event log
        still covers it, only the missed events are replayed — the
        O(delta) resync path — otherwise a RELIST sentinel is emitted
        (seeded consumers must drop their preloaded state: the replay is
        a replacement, not a delta) followed by the full O(bucket) ADDED
        replay."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        now = time.monotonic()
        with self._lock:
            if resume_rv is not None:
                bookmarks = True
                # The log covers the resume point iff its oldest entry is
                # no newer than the first event we'd need (rvs in the log
                # are contiguous — every rv bump emits exactly one event).
                covered = resume_rv >= self._rv or (
                    bool(self._event_log)
                    and self._event_log[0].rv <= resume_rv + 1
                )
                if covered:
                    n = 0
                    for ev in self._event_log:
                        if ev.rv > resume_rv and (
                                kind is None or ev.object.kind == kind):
                            q.put(ev)
                            n += 1
                    self._count_replayed("resume", n)
                else:
                    # Missed events already evicted: the resume point is
                    # too old, fall back to a full replay. The RELIST
                    # sentinel tells a seeded consumer the replay is a
                    # REPLACEMENT, not a delta — without it, an object
                    # deleted while the consumer was down (its DELETED
                    # event evicted from the log) would survive in the
                    # seed forever, since full replay only emits ADDED
                    # for objects that still exist.
                    q.put(WatchEvent("RELIST", None, ts_mono=now, rv=0))
                    resume_rv = None
            if resume_rv is None:
                # Replay current state so late watchers converge (informer-
                # style). Replay shares the stored snapshots — resolved
                # from the per-kind index bucket for kind-scoped
                # subscriptions, never the whole store — and the old
                # deepcopy-the-store-under-the-lock stalled every writer
                # for the whole copy.
                if kind is None:
                    replay: Iterator[Any] = iter(self._objects.values())
                else:
                    replay = iter(self._by_kind.get(kind, {}).values())
                n = 0
                for obj in replay:
                    q.put(WatchEvent("ADDED", obj, ts_mono=now,
                                     rv=obj.metadata.resource_version))
                    n += 1
                self._count_replayed("full", n)
            if bookmarks:
                # Initial bookmark: the snapshot resource version this
                # subscription is consistent with — persist it and pass it
                # back as resume_rv to resync without a relist.
                q.put(WatchEvent("BOOKMARK", None, ts_mono=now,
                                 rv=self._rv))
            self._watchers.append((kind, q, bookmarks))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [w for w in self._watchers if w[1] is not q]
