"""In-memory API server: typed object store with watch, optimistic
concurrency, finalizers and owner-reference garbage collection.

This is the platform's envtest analogue — the reference tests controllers
against a real etcd+apiserver spun up per suite (components/
profile-controller/controllers/suite_test.go:50-72); we provide the same
semantics in-process so every controller test runs in milliseconds, and the
store's interface is the seam where a real K8s client is substituted in a
cluster deployment.

Semantics implemented (the subset the reference's controllers rely on):
- resourceVersion bump on every write; update with a stale version raises
  ConflictError (optimistic concurrency, the retry-on-conflict loops in
  profile_controller.go:150-154).
- delete with finalizers present only sets deletionTimestamp; the object
  goes away when the last finalizer is removed (plugin teardown,
  profile_controller.go Reconcile finalizer path).
- ownerReferences cascade: deleting an owner deletes its dependents
  (how STS->pods and job->pods cleanup behaves for the reference).
- label-selector list; namespaced and cluster-scoped kinds.
- watch: per-subscriber queues receiving ADDED/MODIFIED/DELETED events.

Scaling model (docs/controlplane-perf.md): the store keeps **canonical
immutable snapshots**. Every write deep-copies the inbound object once and
*replaces* the stored snapshot — a snapshot, once stored, is never edited
in place. That invariant is what makes the read path cheap:

- ``get``/``try_get``/``list`` default to ``copy=True`` (a private,
  mutate-then-update-able copy — the read-modify-write idiom every
  controller write loop uses), but read-only callers pass ``copy=False``
  and receive the shared snapshot with **zero** copying.
- ``list`` resolves through per-kind / per-(kind, namespace) secondary
  indexes, so its cost — and, with ``copy=True``, its copy count — scales
  with the number of *matching* objects, never with store size.
- watch events share one event object (and the stored snapshot) across
  all subscribers; late-watcher replay reuses the stored snapshots too.
- ``_cascade_delete`` resolves dependents through an owner-uid index,
  breadth-first, instead of re-scanning the whole store per level.

Zero-copy results are read-only by contract (exactly client-go's shared
informer cache contract). Read-path copies are tallied per verb in
``self.copied`` and exported as
``kftpu_apiserver_objects_copied_total{verb}`` so benches and the CI
``cp-bench-smoke`` stage can assert the O(matches) property by counting,
not timing.
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from copy import deepcopy
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.meta import fresh_identity
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry
from kubeflow_tpu.utils.tracing import SpanContext, Tracer, global_tracer

CLUSTER_SCOPED = {"Namespace", "Profile", "PlatformConfig"}


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    pass


@dataclasses.dataclass
class WatchEvent:
    type: str          # ADDED | MODIFIED | DELETED
    object: Any
    # Observability stamps, set at notify time (zero-cost to consumers
    # that ignore them): when the event was enqueued (monotonic — the
    # watch-delivery-lag measurement point) and the span context of the
    # write that produced it (the write-RV → reconcile trace link).
    ts_mono: float = 0.0
    span_ctx: Optional[SpanContext] = None


Key = Tuple[str, str, str]  # (kind, namespace, name)


def _key(obj: Any) -> Key:
    kind = obj.kind
    ns = "" if kind in CLUSTER_SCOPED else obj.metadata.namespace
    return (kind, ns, obj.metadata.name)


def match_labels(obj: Any, selector: Optional[Dict[str, str]]) -> bool:
    """The list() label-selector predicate, shared with CachedReader so the
    informer cache cannot drift from the server's matching semantics."""
    if not selector:
        return True
    labels = obj.metadata.labels
    return all(labels.get(k) == v for k, v in selector.items())


def _sorted_objs(objs: List[Any]) -> List[Any]:
    return sorted(objs, key=lambda o: (o.metadata.namespace, o.metadata.name))


def index_put(by_kind: Dict[str, Dict[Key, Any]],
              by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
              key: Key, obj: Any) -> None:
    """Insert into the kind / (kind, namespace) index pair. Shared with
    CachedReader so the two index implementations cannot drift."""
    by_kind.setdefault(key[0], {})[key] = obj
    by_kind_ns.setdefault(key[:2], {})[key] = obj


def list_bucket(by_kind: Dict[str, Dict[Key, Any]],
                by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
                kind: str, namespace: Optional[str],
                label_selector: Optional[Dict[str, str]]) -> List[Any]:
    """Resolve a list() query against the index pair: pick the bucket,
    apply the selector. One implementation shared by the server and the
    informer cache so their answers cannot drift. Callers hold their own
    lock and sort/copy the result themselves."""
    if namespace is None or kind in CLUSTER_SCOPED:
        bucket = by_kind.get(kind, {})
    else:
        bucket = by_kind_ns.get((kind, namespace), {})
    return [obj for obj in bucket.values()
            if match_labels(obj, label_selector)]


def index_drop(by_kind: Dict[str, Dict[Key, Any]],
               by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]],
               key: Key) -> None:
    """Remove from the index pair, pruning buckets that empty out (a
    long-lived store/cache must not accumulate one dead dict per kind or
    namespace ever seen)."""
    for mapping, mkey in ((by_kind, key[0]), (by_kind_ns, key[:2])):
        bucket = mapping.get(mkey)
        if bucket is not None:
            bucket.pop(key, None)
            if not bucket:
                del mapping[mkey]


#: Span-name table: f-string per call showed up in sweep profiles.
_VERB_SPAN_NAMES = {
    v: f"apiserver.{v}"
    for v in ("create", "get", "update", "update_status", "delete", "list")
}


class _VerbSpan:
    """Hand-rolled context manager for the API verb hot path: one
    tracer span + one latency observation, without the two nested
    generator context managers the idiomatic form costs per call
    (profiled: ~3% of a whole control-plane sweep)."""

    __slots__ = ("api", "verb", "span")

    def __init__(self, api: "InMemoryApiServer", verb: str, kind: str,
                 name: str, namespace: str):
        self.api = api
        self.verb = verb
        self.span = api.tracer.start(
            _VERB_SPAN_NAMES.get(verb, f"apiserver.{verb}"),
            attrs={"verb": verb, "kind": kind, "name": name,
                   "namespace": namespace},
        )

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        self.api.tracer.finish(self.span)
        self.api.metrics_latency.observe(self.span.duration_s,
                                         verb=self.verb)
        return False


class InMemoryApiServer:
    def __init__(self, registry: MetricsRegistry = global_registry,
                 tracer: Tracer = global_tracer) -> None:
        self._objects: Dict[Key, Any] = {}
        # Secondary indexes (all under self._lock, all holding the same
        # snapshot references as self._objects — replaced together on
        # every write):
        self._by_kind: Dict[str, Dict[Key, Any]] = {}
        self._by_kind_ns: Dict[Tuple[str, str], Dict[Key, Any]] = {}
        self._by_owner: Dict[str, Dict[Key, Any]] = {}   # owner uid -> deps
        self._rv = 0
        self._lock = threading.RLock()
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        # Admission mutators run on create (the PodDefault webhook seam,
        # admission-webhook/main.go:389-470).
        self._mutators: List[Callable[[Any], Any]] = []
        # Read-path deepcopy tally, per verb ("get"/"list"). Deterministic
        # (a pure function of the call sequence), so benches and CI gate on
        # counts instead of wall-clock.
        self.copied: Dict[str, int] = {}
        self.metrics_copied = registry.counter(
            "kftpu_apiserver_objects_copied_total",
            "Objects deep-copied on the API server read path",
            labels=("verb",),
        )
        self.tracer = tracer
        self.metrics_latency = registry.histogram(
            "kftpu_apiserver_request_duration_seconds",
            "API server verb latency",
            labels=("verb",),
        )

    # ----------------- helpers -----------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _count_copies(self, verb: str, n: int) -> None:
        if n <= 0:
            return
        self.copied[verb] = self.copied.get(verb, 0) + n
        self.metrics_copied.inc(n, verb=verb)

    def copied_total(self) -> int:
        return sum(self.copied.values())

    def _verb_span(self, verb: str, kind: str, name: str = "",
                   namespace: str = "") -> "_VerbSpan":
        """One span + latency-histogram observation per API verb call
        (observed on success AND failure — an erroring verb still took
        time). Write verbs additionally set the resulting ``rv`` attr
        inside the verb body (the write-RV the reconcile trace links
        back to)."""
        return _VerbSpan(self, verb, kind, name, namespace)

    def _index_add(self, key: Key, obj: Any) -> None:
        index_put(self._by_kind, self._by_kind_ns, key, obj)
        for ref in obj.metadata.owner_references:
            if ref.uid:
                self._by_owner.setdefault(ref.uid, {})[key] = obj

    def _index_remove(self, key: Key, obj: Any) -> None:
        index_drop(self._by_kind, self._by_kind_ns, key)
        for ref in obj.metadata.owner_references:
            bucket = self._by_owner.get(ref.uid)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self._by_owner[ref.uid]

    def _store(self, key: Key, obj: Any) -> None:
        """Replace (never edit) the stored snapshot, keeping every index in
        step — owner references may have changed on update."""
        old = self._objects.get(key)
        if old is not None:
            self._index_remove(key, old)
        self._objects[key] = obj
        self._index_add(key, obj)

    def _remove(self, key: Key) -> Any:
        obj = self._objects.pop(key)
        self._index_remove(key, obj)
        return obj

    def _notify(self, event: WatchEvent) -> None:
        # Stamp delivery time + the writing span's context on the shared
        # event: the reconciler measures watch-delivery lag against
        # ts_mono and links its reconcile span to span_ctx (one trace
        # from write to status update).
        event.ts_mono = time.monotonic()
        event.span_ctx = self.tracer.current_context()
        # ONE event object shared by every subscriber: the payload is the
        # stored snapshot, which is immutable by contract, so per-watcher
        # deep copies bought nothing but O(watchers) deepcopy per write.
        # Always called with self._lock held, so delivery order == write
        # order — the invariant last-wins consumers (CachedReader) rely on;
        # notifying outside the lock let two racing writers enqueue their
        # events in the wrong order and wedge a cache stale forever.
        for kind, q in list(self._watchers):
            if kind is None or kind == event.object.kind:
                q.put(event)

    def register_mutator(self, fn: Callable[[Any], Any]) -> None:
        with self._lock:
            self._mutators.append(fn)

    def load_snapshot(self, obj: Any) -> None:
        """Restore a persisted object verbatim: identity fields kept, no
        resourceVersion bump, no watch events, indexes maintained — the
        Platform.save/load seam. (Writing into ``_objects`` directly would
        leave the secondary indexes empty.)"""
        with self._lock:
            self._store(_key(obj), obj)

    # ----------------- CRUD -----------------

    def create(self, obj: Any) -> Any:
        with self._verb_span("create", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            obj = deepcopy(obj)
            if not obj.metadata.name:
                raise ApiError(f"{obj.kind}: metadata.name required")
            if obj.kind not in CLUSTER_SCOPED and not obj.metadata.namespace:
                raise ApiError(f"{obj.kind}/{obj.metadata.name}: namespace required")
            key = _key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            for m in self._mutators:
                out = m(obj)
                if out is not None:
                    obj = out
            fresh_identity(obj.metadata)
            obj.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = obj.metadata.resource_version
            obj.metadata.generation = 1
            self._store(key, obj)
            out = deepcopy(obj)
            self._notify(WatchEvent("ADDED", obj))
        return out

    def get(self, kind: str, name: str, namespace: str = "", *,
            copy: bool = True) -> Any:
        """``copy=True`` (default) returns a private mutate-then-update-able
        copy; ``copy=False`` returns the shared snapshot (read-only by
        contract — never mutate it)."""
        with self._verb_span("get", kind, name, namespace), self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            obj = self._objects.get((kind, ns, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            if not copy:
                return obj
            self._count_copies("get", 1)
            return deepcopy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "", *,
                copy: bool = True) -> Optional[Any]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def update(self, obj: Any) -> Any:
        with self._verb_span("update", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise ConflictError(
                    f"{key}: resourceVersion {obj.metadata.resource_version} "
                    f"!= {cur.metadata.resource_version}"
                )
            obj = deepcopy(obj)
            # Identity fields are server-owned.
            obj.metadata.uid = cur.metadata.uid
            obj.metadata.creation_timestamp = cur.metadata.creation_timestamp
            obj.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = obj.metadata.resource_version
            if self._spec_changed(cur, obj):
                obj.metadata.generation = cur.metadata.generation + 1
            removed = (
                obj.metadata.deletion_timestamp is not None
                and not obj.metadata.finalizers
            )
            if removed:
                # Last finalizer cleared: the update completes the delete —
                # don't pay a _store index add just to tear it down again.
                self._remove(key)
                self._notify(WatchEvent("DELETED", obj))
            else:
                self._store(key, obj)
                self._notify(WatchEvent("MODIFIED", obj))
            out = deepcopy(obj)
        if removed:
            # Cascade OUTSIDE the lock (like delete()): a finalizer clear on
            # an owner must not stall all API traffic for the whole
            # dependent-tree teardown.
            self._cascade_delete(obj)
        return out

    @staticmethod
    def _spec_changed(a: Any, b: Any) -> bool:
        sa = getattr(a, "spec", None)
        sb = getattr(b, "spec", None)
        return sa != sb

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._verb_span("delete", kind, name, namespace):
            removed = self._delete_one(kind, name, namespace)
            if removed is not None:
                self._cascade_delete(removed)

    def _delete_one(self, kind: str, name: str, namespace: str) -> Optional[Any]:
        """Delete without cascading; returns the removed object, or None when
        finalizers only marked it (deletionTimestamp set, object retained)."""
        with self._lock:
            ns = "" if kind in CLUSTER_SCOPED else namespace
            key = (kind, ns, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur = deepcopy(cur)
                    cur.metadata.deletion_timestamp = time.time()
                    cur.metadata.resource_version = self._next_rv()
                    self._store(key, cur)
                    self._notify(WatchEvent("MODIFIED", cur))
                return None
            self._remove(key)
            self._notify(WatchEvent("DELETED", cur))
            return cur

    def _cascade_delete(self, owner: Any) -> None:
        """Delete dependents referencing the owner's uid, breadth-first via
        the owner-uid index — the old implementation re-scanned the whole
        store once per dependency *level*."""
        pending: "collections.deque[str]" = collections.deque(
            [owner.metadata.uid]
        )
        while pending:
            uid = pending.popleft()
            with self._lock:
                deps = list(self._by_owner.get(uid, {}).values())
            for dep in deps:
                try:
                    removed = self._delete_one(
                        dep.kind, dep.metadata.name, dep.metadata.namespace
                    )
                except NotFoundError:
                    continue
                if removed is not None:
                    pending.append(removed.metadata.uid)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        *,
        copy: bool = True,
    ) -> List[Any]:
        """Index-resolved list: touches only the (kind) or (kind, namespace)
        bucket, so cost is O(bucket) and copy count (``copy=True``) is
        O(matches) — never O(store). ``copy=False`` returns the shared
        snapshots (read-only by contract)."""
        with self._verb_span("list", kind, namespace=namespace or ""):
            with self._lock:
                out = list_bucket(self._by_kind, self._by_kind_ns,
                                  kind, namespace, label_selector)
                if copy:
                    self._count_copies("list", len(out))
            if copy:
                # Snapshots are immutable once stored, so the copies happen
                # OUTSIDE the lock — a big copy=True list must not stall
                # every concurrent writer for the duration of the deepcopy
                # loop.
                out = [deepcopy(o) for o in out]
            return _sorted_objs(out)

    def list_all(self) -> List[Any]:
        """Every stored snapshot, all kinds, shared zero-copy (read-only by
        contract) — the store-wide enumeration benches and state
        fingerprints use instead of reaching into ``_objects``."""
        with self._lock:
            return list(self._objects.values())

    # ----------------- status + finalizer conveniences -----------------

    def update_status(self, obj: Any) -> Any:
        """Update ONLY the status subresource (concurrent spec writes win)."""
        with self._verb_span("update_status", obj.kind, obj.metadata.name,
                             obj.metadata.namespace) as sp, self._lock:
            key = _key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(f"{key} not found")
            new = deepcopy(cur)
            new.status = deepcopy(obj.status)
            new.metadata.resource_version = self._next_rv()
            sp.attrs["rv"] = new.metadata.resource_version
            self._store(key, new)
            out = deepcopy(new)
            self._notify(WatchEvent("MODIFIED", new))
        return out

    # ----------------- watch -----------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            # Replay current state so late watchers converge (informer-
            # style). Replay shares the stored snapshots: the old
            # deepcopy-the-store-under-the-lock stalled every writer for
            # the whole copy.
            if kind is None:
                replay: Iterator[Any] = iter(self._objects.values())
            else:
                replay = iter(self._by_kind.get(kind, {}).values())
            for obj in replay:
                q.put(WatchEvent("ADDED", obj, ts_mono=time.monotonic()))
            self._watchers.append((kind, q))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]
