"""Reconciler kernel: work queue, watch wiring, create-or-update helpers.

The controller harness every platform controller runs on, mirroring what
the reference gets from controller-runtime plus its shared reconcilehelper
(components/common/reconcilehelper/util.go: idempotent create-or-update with
field-copy diffing) and the monitoring pattern every controller repeats
(profile-controller/controllers/monitoring.go:24-78) — here the kernel
provides metrics and heartbeat for free (SURVEY.md §7.2).
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import heapq
import queue as queue_mod
import threading
import time
import traceback
import weakref
from copy import deepcopy as _deepcopy
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.runtime.apiserver import (
    CLUSTER_SCOPED,
    ConflictError,
    InMemoryApiServer,
    NotFoundError,
    _key,
    _sorted_objs,
    index_drop,
    index_put,
    list_bucket,
)
from kubeflow_tpu.controlplane.runtime.ratelimiter import (
    ExponentialBackoffLimiter,
)
from kubeflow_tpu.utils import get_logger, locktrace
from kubeflow_tpu.utils.monitoring import (
    MetricsRegistry,
    global_registry,
    sanitize_metric_name,
)
from kubeflow_tpu.utils.tracing import SpanContext, Tracer, global_tracer


@dataclasses.dataclass
class Result:
    requeue_after: Optional[float] = None   # seconds


class CachedReader:
    """Informer-style read cache: serves ``get``/``try_get``/``list`` for
    watched kinds straight from the watch stream (the client-go shared
    informer / Store analogue), so controller read loops never pay an API
    round trip — and, in-process, never pay a deepcopy: cached objects ARE
    the server's immutable snapshots, shared by reference.

    Contract mirrors client-go:
    - ``copy`` defaults to True — the same always-safe default as every
      API-server implementation, so a controller behaves identically
      whether its ``reader`` is the cache or the API itself. Read-only
      loops opt into the zero-copy path with ``copy=False``, whose results
      are **read-only by contract** (mutating one is the client-go
      mutate-a-cached-object programming error);
    - kinds not subscribed fall through to the underlying API — which may
      be a ``ChaosApiServer``, so fault injection still sits *ahead* of
      the cache for everything that actually leaves the informer;
    - freshness: events are enqueued synchronously at write time and
      drained on every read (``sync``), so in-process reads always observe
      their own writes;
    - resync: subscriptions opt into watch BOOKMARK events, and the last
      bookmarked resource version per kind is tracked (``resume_rv``). A
      restarted reader seeded from persisted state resubscribes with
      ``watch_kind(kind, resume_rv=..., seed=...)`` and receives only the
      events it missed — never an O(store) relist (the client-go
      reflector's resumeRV path; satellite of ISSUE 6).
    """

    def __init__(self, api: Any):
        self.api = api
        self._watches: Dict[str, Any] = {}     # kind -> watch queue
        self._store: Dict[Tuple[str, str, str], Any] = {}
        self._by_kind: Dict[str, Dict[Tuple[str, str, str], Any]] = {}
        self._by_kind_ns: Dict[Tuple[str, str], Dict[Tuple[str, str, str], Any]] = {}
        # Last seen resource version per kind (bookmarks + events), under
        # self._lock: what a restart passes back as resume_rv.
        self._resume_rv: Dict[str, int] = {}
        # Store lock: guards the local store + indexes only, held per-apply
        # and per-lookup — never across a queue drain. Draining is
        # serialized PER KIND (one lock per subscription), so concurrent
        # reconciles reading different kinds never queue up behind an
        # unrelated drain (the old single-lock sync() drained every
        # subscription under one lock on every read).
        self._lock = threading.Lock()
        self._drain_locks: Dict[str, threading.Lock] = {}
        self._sub_lock = threading.Lock()      # _watches/_drain_locks registry

    def watch_kind(self, kind: str, *, resume_rv: Optional[int] = None,
                   seed: Tuple[Any, ...] = ()) -> None:
        """Subscribe to ``kind``. ``seed`` preloads the local store with
        objects restored from persisted state (shared references, no
        copies); ``resume_rv`` asks the server to replay only events newer
        than that version — together they are the restart path: seed from
        the snapshot/WAL, resume from the last bookmark, skip the relist."""
        with self._sub_lock:
            if kind in self._watches:
                return
            self._drain_locks[kind] = threading.Lock()
            # The seed is only sound on the resume path: a full ADDED
            # replay (resume_rv=None, or a backend without resume
            # support) has no RELIST sentinel, so a seeded object that
            # was deleted while the reader was down would never be
            # removed — the replay rebuilds the full state anyway, so
            # the seed buys nothing there.
            if seed and resume_rv is not None:
                with self._lock:
                    for obj in seed:
                        key = _key(obj)
                        self._store[key] = obj
                        index_put(self._by_kind, self._by_kind_ns, key, obj)
            try:
                q = self.api.watch(kind, resume_rv=resume_rv,
                                   bookmarks=True)
            except TypeError:
                # Backends predating bookmark support (duck-typed fakes,
                # the kubectl adapter): plain subscription, full replay —
                # drop any seeded state for the ghost-object reason above.
                with self._lock:
                    for key in list(self._by_kind.get(kind, {})):
                        self._store.pop(key, None)
                        index_drop(self._by_kind, self._by_kind_ns, key)
                q = self.api.watch(kind)
            self._watches[kind] = q

    def resume_rv(self, kind: str) -> Optional[int]:
        """The last resource version this cache is known consistent with
        for ``kind`` (from bookmarks and applied events) — persist it and
        hand it back to ``watch_kind(resume_rv=...)`` after a restart."""
        self._sync_kind(kind)
        with self._lock:
            return self._resume_rv.get(kind)

    def caches(self, kind: str) -> bool:
        return kind in self._watches

    def _apply_locked(self, ev: Any, kind: str) -> None:
        if ev.rv:
            self._resume_rv[kind] = ev.rv
        if getattr(ev, "type", None) == "RELIST":
            # The server could not honor our resume_rv: the ADDED events
            # that follow are a REPLACEMENT for this kind, so the seeded
            # store must be dropped first — an object deleted while we
            # were down is in the seed but not in the replay, and nothing
            # else would ever remove it.
            for key in list(self._by_kind.get(kind, {})):
                self._store.pop(key, None)
                index_drop(self._by_kind, self._by_kind_ns, key)
            return
        if ev.object is None:
            # BOOKMARK: nothing to store, only the rv watermark above.
            return
        key = _key(ev.object)
        if ev.type == "DELETED":
            self._store.pop(key, None)
            index_drop(self._by_kind, self._by_kind_ns, key)
        else:
            self._store[key] = ev.object
            index_put(self._by_kind, self._by_kind_ns, key, ev.object)

    def _sync_kind(self, kind: str) -> int:
        """Drain one kind's subscription into the local store; returns
        events applied. The drain lock is taken blocking: read-your-own-
        writes freshness requires waiting for a drain already holding our
        event, not skipping it. Events are collected first and applied
        under one short store-lock acquisition."""
        q = self._watches.get(kind)
        lock = self._drain_locks.get(kind)
        if q is None or lock is None:
            return 0
        with lock:
            events: List[Any] = []
            while True:
                try:
                    events.append(q.get(block=False))
                except queue_mod.Empty:
                    break
            if not events:
                return 0
            with self._lock:
                for ev in events:
                    self._apply_locked(ev, kind)
        # Bookmarks advance the rv watermark but carry no object; the
        # returned count keeps its meaning of "state changes applied".
        return sum(1 for ev in events if ev.object is not None)

    def sync(self) -> int:
        """Drain every subscription into the local store; returns events
        applied. Hot-path reads use the per-kind drain instead."""
        with self._sub_lock:
            kinds = list(self._watches)
        return sum(self._sync_kind(k) for k in kinds)

    # -- reads --

    def get(self, kind: str, name: str, namespace: str = "", *,
            copy: bool = True) -> Any:
        if not self.caches(kind):
            return self.api.get(kind, name, namespace, copy=copy)
        self._sync_kind(kind)
        ns = "" if kind in CLUSTER_SCOPED else namespace
        with self._lock:
            obj = self._store.get((kind, ns, name))
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        return _deepcopy(obj) if copy else obj

    def try_get(self, kind: str, name: str, namespace: str = "", *,
                copy: bool = True) -> Optional[Any]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        *,
        copy: bool = True,
        limit: Optional[int] = None,
        continue_: Optional[str] = None,
    ) -> List[Any]:
        if limit is not None or continue_ is not None:
            # Paginated walks need the server's snapshot-pinned continue
            # tokens; the local cache has no snapshot registry. (client-go
            # informers likewise serve full lists only — paginated reads
            # go to the apiserver.)
            return self.api.list(kind, namespace, label_selector,
                                 copy=copy, limit=limit,
                                 continue_=continue_)
        if not self.caches(kind):
            return self.api.list(kind, namespace, label_selector, copy=copy)
        self._sync_kind(kind)
        with self._lock:
            out = list_bucket(self._by_kind, self._by_kind_ns,
                              kind, namespace, label_selector)
        if copy:
            out = [_deepcopy(o) for o in out]
        return _sorted_objs(out)

    def close(self) -> None:
        with self._sub_lock:
            for q in self._watches.values():
                self.api.stop_watch(q)
            self._watches.clear()
            self._drain_locks.clear()
        with self._lock:
            self._store.clear()
            self._by_kind.clear()
            self._by_kind_ns.clear()


class Controller:
    """Base class: subclasses set WATCH_KINDS and implement reconcile(key).

    ``key`` is (namespace, name) of the primary kind (WATCH_KINDS[0]);
    events on secondary kinds are mapped back to the primary via
    ``map_to_primary`` (the reference's Watches+handler.EnqueueRequestsFrom
    MapFunc wiring, notebook_controller.go:512-609).
    """

    NAME = "controller"
    WATCH_KINDS: Tuple[str, ...] = ()

    def __init__(self, api: InMemoryApiServer, registry: MetricsRegistry = global_registry):
        self.api = api
        # Read surface for list/get loops that do NOT mutate-then-update.
        # Defaults to the API itself; ControllerManager.register swaps in
        # its shared CachedReader (informer cache) when the backend
        # supports synchronous watches.
        self.reader: Any = api
        self.log = get_logger(self.NAME)
        # Sanitized interpolation: NAMEs like "fake-kubelet" must not
        # produce exposition-illegal metric names (CI obs-smoke parses
        # the scrape).
        mname = sanitize_metric_name(self.NAME)
        self.metrics_reconcile = registry.counter(
            # kftpu: allow(KF103): per-controller name family
            # `kftpu_<controller>_reconcile_total` — NAME is a class
            # constant fed through sanitize_metric_name, and the family
            # is documented as a pattern row in docs/observability.md.
            f"kftpu_{mname}_reconcile_total",
            f"Reconcile outcomes for {self.NAME}",
            labels=("result",),
        )
        self.metrics_retries = registry.counter(
            # kftpu: allow(KF103): same pattern family as above
            # (`kftpu_<controller>_retries_total`), sanitized + documented.
            f"kftpu_{mname}_retries_total",
            f"Requeues after failed reconciles for {self.NAME}",
            labels=("reason",),
        )
        self.heartbeat = registry.heartbeat(self.NAME)

    # -- override points --

    def reconcile(self, namespace: str, name: str) -> Result:
        raise NotImplementedError

    def map_to_primary(self, obj: Any) -> Optional[Tuple[str, str]]:
        """Map a secondary-kind object to the primary key. Default: follow
        the controller ownerReference (by name) or the job/notebook label."""
        for ref in obj.metadata.owner_references:
            if ref.kind == self.WATCH_KINDS[0]:
                return (obj.metadata.namespace, ref.name)
        return None


class ControllerManager:
    """Runs a set of controllers against one API server.

    Two modes:
    - ``run_until_idle()``: deterministic synchronous draining for tests and
      tpuctl --wait (process events → reconcile → repeat until no work,
      honouring due requeues). The analogue of envtest's eventually-
      consistent assertions but without sleeps.
    - ``start()/stop()``: background thread pumping the same loop, for
      long-running services.

    ``workers`` (default 1, preserving strictly-serial dispatch) sizes a
    reconcile worker pool with client-go workqueue semantics
    (the ``MaxConcurrentReconciles`` analogue):

    - distinct keys reconcile concurrently, up to ``workers`` at a time;
    - a key is NEVER reconciled concurrently with itself — dequeued keys
      enter an in-flight set, and enqueues for an in-flight key mark it
      *dirty* instead of queueing a duplicate;
    - a dirty key re-enqueues exactly once when its reconcile completes,
      so events arriving mid-reconcile are neither lost nor duplicated
      (client-go's dirty-set-checked-in-Done contract).
    """

    #: Consecutive conflicts on one key retried immediately (the standard
    #: informer dance: re-read, re-apply). Beyond this the key is fighting
    #: another writer — fall back to the exponential limiter so a conflict
    #: storm can't spin the queue hot.
    CONFLICT_IMMEDIATE_RETRIES = 5

    #: Causal links kept per pending key: events that dedup into an
    #: already-queued key append their write span, capped so a hot key
    #: cannot grow an unbounded link list.
    MAX_LINKS_PER_KEY = 4

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        limiter: Optional[ExponentialBackoffLimiter] = None,
        use_cache: Optional[bool] = None,
        tracer: Tracer = global_tracer,
        workers: int = 1,
    ):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.api = api
        self.tracer = tracer
        self.workers = int(workers)
        self._executor: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self.controllers: List[Controller] = []
        self.limiter = limiter or ExponentialBackoffLimiter()
        self._queues: List[Any] = []
        # Shared informer cache for controller reads. Enabled only when the
        # backend delivers watch events synchronously at write time (the
        # in-memory server, possibly behind a chaos/fault wrapper exposing
        # .inner) — the kubectl backend's poll-based watch would make cache
        # reads lag direct reads, so it keeps reader == api.
        if use_cache is None:
            use_cache = isinstance(
                getattr(api, "inner", api), InMemoryApiServer
            )
        self._cache: Optional[CachedReader] = \
            CachedReader(api) if use_cache else None
        # deque + set mirror: O(1) at both ends — chaos-scale event storms
        # made the old list's membership scans and pop(0) quadratic.
        self._pending: "collections.deque[Tuple[Controller, Tuple[str, str]]]" = \
            collections.deque()
        self._pending_set: set = set()
        # Per-pending-key observability meta (under self._lock, popped at
        # dequeue): first-enqueue monotonic time (queue-wait measurement)
        # and the span contexts of the writes whose events enqueued it
        # (reconcile-span links).
        self._pending_meta: Dict[
            Tuple[Controller, Tuple[str, str]],
            Tuple[float, List[SpanContext]],
        ] = {}
        # Per-key serialization state (client-go workqueue semantics):
        # keys currently executing in the worker pool, and keys that
        # received an enqueue while in flight (value: earliest-arrival
        # monotonic time + causal links of the collapsed events) —
        # re-enqueued exactly once at completion.
        self._inflight: set = set()
        self._dirty: Dict[Tuple[Controller, Tuple[str, str]],
                          Tuple[float, List[SpanContext]]] = {}
        # Backoff/requeue timers keyed on the MONOTONIC clock: wall-clock
        # (time.time) deadlines misfire every parked timer on an NTP step
        # backward and stall them all on a jump forward.
        self._timers: List[Tuple[float, int, Controller, Tuple[str, str]]] = []
        self._timer_seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Built through the locktrace factory: a plain Lock normally, a
        # traced one under the chaos soaks' lock-order detector.
        self._lock = locktrace.lock("manager.pending")
        # Optional workqueue oracle (utils/locktrace.WorkqueueOracle):
        # when installed, _execute brackets every reconcile with
        # enter/exit so the per-key never-concurrent invariant is
        # CHECKED under the parallel soaks instead of trusted.
        self.oracle = None
        self.log = get_logger("manager")
        # Queue-health gauges (client-go workqueue_depth analogues). On a
        # shared registry the first manager's callbacks win, matching the
        # one-manager-per-process deployment shape; the weakref keeps that
        # first-wins registration from pinning a discarded manager alive.
        wref = weakref.ref(self)

        def _of_manager(attr_len: Callable[["ControllerManager"], float]):
            def read() -> float:
                m = wref()
                return attr_len(m) if m is not None else 0.0
            return read

        registry.gauge(
            "kftpu_workqueue_depth",
            "Reconcile keys waiting in the immediate work queue",
            fn=_of_manager(lambda m: float(len(m._pending))),
        )
        registry.gauge(
            "kftpu_workqueue_backoff_pending",
            "Reconcile keys parked on requeue/backoff timers",
            fn=_of_manager(lambda m: float(len(m._timers))),
        )
        registry.gauge(
            "kftpu_workqueue_failing_keys",
            "Keys with a nonzero failure count in the backoff limiter",
            fn=_of_manager(lambda m: float(m.limiter.tracked_keys())),
        )
        registry.gauge(
            "kftpu_workqueue_inflight",
            "Reconciles currently executing in the worker pool",
            fn=_of_manager(lambda m: float(len(m._inflight))),
        )
        # Latency decomposition (ISSUE 4): where a key's end-to-end time
        # goes — write → watch delivery → queue wait → reconcile. Queue
        # wait and watch lag get a wider tail than the verb/reconcile
        # histograms: at fleet scale a key legitimately waits tens of
        # seconds behind thousands of peers, and clamping at 5s would
        # erase exactly the signal this layer exists to expose.
        from kubeflow_tpu.utils.monitoring import DEFAULT_LATENCY_BUCKETS

        wait_buckets = DEFAULT_LATENCY_BUCKETS + (10.0, 30.0, 60.0, 120.0)
        self.metrics_reconcile_latency = registry.histogram(
            "kftpu_reconcile_duration_seconds",
            "Reconcile execution latency",
            labels=("controller", "result"),
        )
        self.metrics_queue_wait = registry.histogram(
            "kftpu_workqueue_wait_seconds",
            "Enqueue-to-dequeue wait in the immediate work queue",
            labels=("controller",),
            buckets=wait_buckets,
        )
        self.metrics_watch_lag = registry.histogram(
            "kftpu_watch_delivery_lag_seconds",
            "Write-to-drain lag of watch events",
            labels=("controller",),
            buckets=wait_buckets,
        )

    def register(self, ctl: Controller) -> None:
        self.controllers.append(ctl)
        for i, kind in enumerate(ctl.WATCH_KINDS):
            q = self.api.watch(kind)
            self._queues.append((ctl, i == 0, q))
            if self._cache is not None:
                self._cache.watch_kind(kind)
        if self._cache is not None:
            ctl.reader = self._cache

    def unregister(self, ctl: Controller) -> None:
        """Release a controller's watch queues and drop its pending work.
        (Registered watches used to leak: a discarded manager's queues kept
        accumulating a copy of every matching event forever.)"""
        with self._lock:
            released = [e[2] for e in self._queues if e[0] is ctl]
            self._queues = [e for e in self._queues if e[0] is not ctl]
            if ctl in self.controllers:
                self.controllers.remove(ctl)
            self._pending = collections.deque(
                (c, k) for c, k in self._pending if c is not ctl
            )
            self._pending_set = {(c, k) for c, k in self._pending_set
                                 if c is not ctl}
            self._pending_meta = {pk: m for pk, m in self._pending_meta.items()
                                  if pk[0] is not ctl}
            self._dirty = {pk: m for pk, m in self._dirty.items()
                           if pk[0] is not ctl}
            self._timers = [t for t in self._timers if t[2] is not ctl]
            heapq.heapify(self._timers)
        ctl.reader = ctl.api
        # stop_watch outside the manager lock: it takes the API server's
        # lock, and no path holds them in the opposite order.
        for q in released:
            self.api.stop_watch(q)

    def close(self) -> None:
        """Tear the manager down: stop the background thread, release every
        registered watch queue and the shared informer cache. Tests and
        benches that build throwaway managers call this so discarded
        managers stop receiving (and buffering) every future event."""
        self.stop()
        for ctl in list(self.controllers):
            self.unregister(ctl)
        if self._cache is not None:
            self._cache.close()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    # ------------- queue pumping -------------

    def _drain_watches(self) -> int:
        n = 0
        now = time.monotonic()
        for ctl, primary, q in self._queues:
            while True:
                # Non-blocking get: empty()-then-get() wedges a drainer
                # that races another consumer for the last event.
                try:
                    ev = q.get(block=False)
                except queue_mod.Empty:
                    break
                n += 1
                if ev.object is None:
                    # BOOKMARK (a bookmark-opted backend): no key to map.
                    continue
                if ev.ts_mono > 0:
                    # Write-time → drain-time lag; under chaos watch-lag
                    # injection this provably includes the injected delay.
                    # The writing span's trace id rides along as the
                    # bucket exemplar (ISSUE 15) — a burning watch-lag
                    # objective then names the exact write→watch trace.
                    self.metrics_watch_lag.observe(
                        max(0.0, now - ev.ts_mono), controller=ctl.NAME,
                        exemplar=ev.span_ctx[0] if ev.span_ctx else None)
                if primary:
                    key = (ev.object.metadata.namespace, ev.object.metadata.name)
                else:
                    key = ctl.map_to_primary(ev.object)
                if key is not None:
                    self._enqueue(ctl, key, link=ev.span_ctx)
        return n

    def _pending_add_locked(self, ctl: Controller, key: Tuple[str, str],
                            link: Optional[SpanContext] = None) -> None:
        if ctl not in self.controllers:
            # unregister() raced a pump thread still draining the released
            # queue: drop the key instead of reconciling a controller the
            # caller already tore down.
            return
        pkey = (ctl, key)
        if pkey in self._inflight:
            # The key is reconciling right now: mark it dirty so it
            # re-enqueues exactly once on completion. Queueing it again
            # here would let a second worker reconcile it concurrently
            # with itself; dropping it would lose the event. The arrival
            # time rides along so the queue-wait histogram counts the
            # whole wait, not just the post-completion sliver.
            entry = self._dirty.setdefault(pkey, (time.monotonic(), []))
            if link is not None and len(entry[1]) < self.MAX_LINKS_PER_KEY:
                entry[1].append(link)
            return
        if pkey not in self._pending_set:
            self._pending_set.add(pkey)
            self._pending.append(pkey)
            self._pending_meta[pkey] = (
                time.monotonic(), [link] if link is not None else []
            )
        elif link is not None:
            # Deduped into an existing entry: keep the causal link (bounded)
            # so the one reconcile that retires N collapsed events can
            # point back at each triggering write.
            meta = self._pending_meta.get(pkey)
            if meta is not None and len(meta[1]) < self.MAX_LINKS_PER_KEY:
                meta[1].append(link)

    def _enqueue(self, ctl: Controller, key: Tuple[str, str],
                 link: Optional[SpanContext] = None) -> None:
        with self._lock:
            self._pending_add_locked(ctl, key, link)

    def _due_timers(self) -> None:
        # Monotonic deadlines: queue-wait/backoff math must not misfire
        # (clock stepped back) or stall (stepped forward) on a wall-clock
        # jump — timers used to mix time.time() here with time.monotonic()
        # on the queue-wait side.
        now = time.monotonic()
        with self._lock:
            while self._timers and self._timers[0][0] <= now:
                _, _, ctl, key = heapq.heappop(self._timers)
                self._pending_add_locked(ctl, key)

    def _schedule(self, ctl: Controller, key: Tuple[str, str], after: float) -> None:
        with self._lock:
            self._timer_seq += 1
            heapq.heappush(
                self._timers,
                (time.monotonic() + after, self._timer_seq, ctl, key),
            )

    def _take_locked(self) -> Optional[Tuple[Controller, Tuple[str, str], Any]]:
        """Pop the next pending key and mark it in flight (caller holds
        the lock). Every key in ``_pending`` is by construction NOT in
        flight — enqueues for in-flight keys divert to the dirty set — so
        whatever this returns is safe to reconcile concurrently with
        every other dequeued key."""
        if not self._pending:
            return None
        ctl, key = self._pending.popleft()
        self._pending_set.discard((ctl, key))
        meta = self._pending_meta.pop((ctl, key), None)
        self._inflight.add((ctl, key))
        return (ctl, key, meta)

    def _finish_key(self, ctl: Controller, key: Tuple[str, str]) -> None:
        """Retire an in-flight key; a key marked dirty while reconciling
        re-enqueues exactly once, carrying the collapsed events' causal
        links (client-go's Done())."""
        with self._lock:
            pkey = (ctl, key)
            self._inflight.discard(pkey)
            entry = self._dirty.pop(pkey, None)
            if entry is not None:
                dirty_since, links = entry
                self._pending_add_locked(ctl, key)
                meta = self._pending_meta.get(pkey)
                if meta is not None:
                    # Queue wait starts at the event's ARRIVAL, not at
                    # this completion — the coalesced event waited the
                    # whole reconcile out.
                    self._pending_meta[pkey] = (
                        dirty_since,
                        meta[1] + links[:self.MAX_LINKS_PER_KEY],
                    )

    def _process_one(self) -> bool:
        with self._lock:
            item = self._take_locked()
        if item is None:
            return False
        self._execute(*item)
        return True

    def _execute(self, ctl: Controller, key: Tuple[str, str],
                 meta: Optional[Tuple[float, List[SpanContext]]]) -> None:
        oracle = self.oracle
        if oracle is not None:
            oracle.enter(ctl.NAME, key)
        try:
            self._reconcile_once(ctl, key, meta)
        finally:
            # The in-flight reservation MUST release even on an exception
            # escaping the handler ladder (BaseException), or the key
            # wedges un-reconcilable forever.
            if oracle is not None:
                oracle.exit(ctl.NAME, key)
            self._finish_key(ctl, key)

    def _reconcile_once(self, ctl: Controller, key: Tuple[str, str],
                        meta: Optional[Tuple[float, List[SpanContext]]]) -> None:
        links: List[SpanContext] = []
        if meta is not None:
            links = meta[1]
            self.metrics_queue_wait.observe(
                max(0.0, time.monotonic() - meta[0]), controller=ctl.NAME,
                exemplar=links[0][0] if links else None)
        lkey = (ctl.NAME, key)
        # The reconcile span ADOPTS the trace of the write that enqueued it
        # (first link), so one trace id covers write → watch → reconcile →
        # the status updates made inside (those nest via the contextvar).
        with self.tracer.span(
            "reconcile",
            attrs={"controller": ctl.NAME, "namespace": key[0],
                   "name": key[1]},
            links=links,
            trace_id=links[0][0] if links else None,
        ) as span:
            outcome = "ok"
            try:
                res = ctl.reconcile(*key) or Result()
                ctl.metrics_reconcile.inc(result="ok")
                self.limiter.forget(lkey)
                if res.requeue_after is not None:
                    span.attrs["requeue_after_s"] = res.requeue_after
                    self._schedule(ctl, key, res.requeue_after)
            except ConflictError:
                # Stale read: immediate requeue (re-read, re-apply — the
                # standard informer dance) while the conflicts look
                # transient; a key that keeps losing the write race backs
                # off instead.
                outcome = "conflict"
                ctl.metrics_reconcile.inc(result="conflict")
                ctl.metrics_retries.inc(reason="conflict")
                delay = self.limiter.next_delay(lkey)
                if self.limiter.failures(lkey) <= self.CONFLICT_IMMEDIATE_RETRIES:
                    self._enqueue(ctl, key)
                else:
                    span.attrs["backoff_s"] = delay
                    self._schedule(ctl, key, delay)
            except NotFoundError:
                # A NotFound from arbitrary API calls mid-reconcile is a
                # race (dependent deleted under us, injected fault), not
                # proof the primary is gone — retry with backoff; if the
                # primary really was deleted the next pass exits cleanly
                # via try_get.
                outcome = "gone"
                ctl.metrics_reconcile.inc(result="gone")
                ctl.metrics_retries.inc(reason="not_found")
                delay = self.limiter.next_delay(lkey)
                span.attrs["backoff_s"] = delay
                self._schedule(ctl, key, delay)
            except Exception:
                outcome = "error"
                ctl.metrics_reconcile.inc(result="error")
                ctl.metrics_retries.inc(reason="error")
                ctl.log.error(
                    f"reconcile {key} failed:\n{traceback.format_exc()}"
                )
                delay = self.limiter.next_delay(lkey)
                span.attrs["backoff_s"] = delay
                self._schedule(ctl, key, delay)
            span.attrs["outcome"] = outcome
        self.metrics_reconcile_latency.observe(
            span.duration_s, controller=ctl.NAME, result=outcome,
            exemplar=span.trace_id)
        ctl.heartbeat.beat()

    # ------------- worker-pool dispatch -------------

    def _ensure_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix="kftpu-reconcile",
            )
        return self._executor

    def _process_batch(self) -> int:
        """One dispatch round: drain the pending queue through the worker
        pool, at most ``workers`` keys in flight at a time, until both the
        queue and the pool are empty. Returns reconciles executed.

        The sliding window (take-as-slots-free, not take-everything-up-
        front) matters twice: the ``kftpu_workqueue_inflight`` gauge
        reads keys actually EXECUTING (its documented triage meaning),
        and events for keys still waiting in pending coalesce into the
        queued entry instead of dirty-diverting into a wasted second
        reconcile. Mid-round enqueues (dirty completions, conflict
        retries) are picked up in the same round; growth is bounded —
        watch events only drain between rounds and repeated conflicts
        park on the backoff limiter — so the round terminates."""
        ex: Optional[concurrent.futures.ThreadPoolExecutor] = None
        futures: set = set()
        done = 0
        while True:
            while len(futures) < self.workers:
                with self._lock:
                    item = self._take_locked()
                if item is None:
                    break
                if ex is None:
                    ex = self._ensure_executor()
                futures.add(ex.submit(self._execute, *item))
            if not futures:
                return done
            finished, futures = concurrent.futures.wait(
                futures, return_when=concurrent.futures.FIRST_COMPLETED)
            for f in finished:
                f.result()
                done += 1

    def is_idle(self) -> bool:
        """No queued reconciles, nothing executing or dirty in the worker
        pool, and no undrained watch events — used by the availability
        prober: a stale heartbeat is only a wedge when there is work
        waiting."""
        with self._lock:
            if self._pending or self._inflight or self._dirty:
                return False
        return all(q.empty() for _, _, q in self._queues)

    def _fast_forward_timers(self, within: float) -> None:
        with self._lock:
            while self._timers and (
                self._timers[0][0] - time.monotonic() <= within
            ):
                _, _, ctl, key = heapq.heappop(self._timers)
                self._pending_add_locked(ctl, key)

    def kick_timers(self, within: float) -> None:
        """Fire every parked requeue timer due within ``within`` seconds
        EXACTLY ONCE (enqueue its key now). The storm/soak drivers' tick
        primitive: ``run_until_idle(include_timers_within=W)`` with W
        past a park interval re-fires a still-parked key every drain pass
        (the documented spin), while one kick before a narrow-window
        drain retries each parked gang once per tick."""
        self._fast_forward_timers(within)

    def run_until_idle(self, max_iterations: int = 10000, include_timers_within: float = 0.0) -> int:
        """Drain watches + queue until no immediate work remains. Returns the
        number of reconciles executed. Timers due within
        ``include_timers_within`` seconds are fast-forwarded (lets tests
        exercise requeue-after logic without sleeping).

        With ``workers > 1`` each drain round dispatches every pending key
        concurrently (deterministic final state — the store converges to
        the same fixpoint — though reconcile interleavings, and hence the
        exact reconcile count, may vary run to run)."""
        done = 0
        for _ in range(max_iterations):
            self._drain_watches()
            self._due_timers()
            if include_timers_within > 0:
                self._fast_forward_timers(include_timers_within)
            n = self._process_batch() if self.workers > 1 \
                else int(self._process_one())
            if n == 0:
                if self._drain_watches() == 0:
                    return done
                continue
            done += n
        # Serial mode budgets reconciles (one per loop pass); batch mode
        # budgets dispatch ROUNDS — cumulative reconciles may legitimately
        # exceed max_iterations there (dirty re-enqueues cost extra
        # passes), and a livelock still shows up as endless nonzero
        # rounds, so only round exhaustion raises.
        raise RuntimeError(
            f"run_until_idle did not converge in {max_iterations} iterations "
            "(reconcile livelock — controllers keep producing events)"
        )

    # ------------- background mode -------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self._drain_watches()
                self._due_timers()
                n = self._process_batch() if self.workers > 1 \
                    else int(self._process_one())
                if n == 0:
                    time.sleep(0.01)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None


# --------------------------------------------------------------------------
# create_or_update: the reconcilehelper equivalent
# --------------------------------------------------------------------------

def create_or_update(
    api: InMemoryApiServer,
    desired: Any,
    *,
    copy_fields: Optional[Callable[[Any, Any], bool]] = None,
) -> Any:
    """Idempotently ensure ``desired`` exists; if present, copy the mutable
    fields onto the live object and update only when something changed
    (components/common/reconcilehelper/util.go:18-107's Deployment/Service/
    VirtualService helpers generalised).

    ``copy_fields(live, desired) -> changed`` defaults to comparing+copying
    ``spec`` plus labels/annotations — the same field set the reference's
    Copy*Fields functions sync.

    The steady-state call is a no-op (idempotent second pass), so for the
    default field set the live object is first read zero-copy and compared
    without mutation; only a detected drift pays the private copy + update.
    A custom ``copy_fields`` mutates its ``live`` argument, so that path
    always reads a private copy.

    The return value is READ-ONLY by contract: on the no-drift fast path
    it is the store's shared snapshot (every other path happens to return
    a private object, but callers must not rely on that). A caller that
    wants to mutate-then-update afterwards re-reads with
    ``api.get(..., copy=True)``.
    """
    if copy_fields is None:
        probe = api.try_get(
            desired.kind, desired.metadata.name, desired.metadata.namespace,
            copy=False,
        )
        if probe is not None and (
            (getattr(desired, "spec", None) is None
             or probe.spec == desired.spec)
            and all(
                {**getattr(probe.metadata, f), **getattr(desired.metadata, f)}
                == getattr(probe.metadata, f)
                for f in ("labels", "annotations")
            )
        ):
            return probe
    # Missing, drifted, or custom copy_fields: read a private copy (the
    # same informer-read fault surface as before) and apply below.
    live = api.try_get(
        desired.kind, desired.metadata.name, desired.metadata.namespace
    )
    if live is None:
        return api.create(desired)

    def default_copy(live_obj: Any, want: Any) -> bool:
        changed = False
        if getattr(want, "spec", None) is not None and live_obj.spec != want.spec:
            live_obj.spec = want.spec
            changed = True
        for field in ("labels", "annotations"):
            want_map = getattr(want.metadata, field)
            live_map = getattr(live_obj.metadata, field)
            merged = {**live_map, **want_map}
            if merged != live_map:
                setattr(live_obj.metadata, field, merged)
                changed = True
        return changed

    fn = copy_fields or default_copy
    if fn(live, desired):
        return api.update(live)
    return live
