"""Reconciler kernel: work queue, watch wiring, create-or-update helpers.

The controller harness every platform controller runs on, mirroring what
the reference gets from controller-runtime plus its shared reconcilehelper
(components/common/reconcilehelper/util.go: idempotent create-or-update with
field-copy diffing) and the monitoring pattern every controller repeats
(profile-controller/controllers/monitoring.go:24-78) — here the kernel
provides metrics and heartbeat for free (SURVEY.md §7.2).
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import threading
import time
import traceback
import weakref
from copy import deepcopy as _deepcopy
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.runtime.apiserver import (
    CLUSTER_SCOPED,
    ConflictError,
    InMemoryApiServer,
    NotFoundError,
    _key,
    _sorted_objs,
    index_drop,
    index_put,
    list_bucket,
)
from kubeflow_tpu.controlplane.runtime.ratelimiter import (
    ExponentialBackoffLimiter,
)
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import (
    MetricsRegistry,
    global_registry,
    sanitize_metric_name,
)
from kubeflow_tpu.utils.tracing import SpanContext, Tracer, global_tracer


@dataclasses.dataclass
class Result:
    requeue_after: Optional[float] = None   # seconds


class CachedReader:
    """Informer-style read cache: serves ``get``/``try_get``/``list`` for
    watched kinds straight from the watch stream (the client-go shared
    informer / Store analogue), so controller read loops never pay an API
    round trip — and, in-process, never pay a deepcopy: cached objects ARE
    the server's immutable snapshots, shared by reference.

    Contract mirrors client-go:
    - ``copy`` defaults to True — the same always-safe default as every
      API-server implementation, so a controller behaves identically
      whether its ``reader`` is the cache or the API itself. Read-only
      loops opt into the zero-copy path with ``copy=False``, whose results
      are **read-only by contract** (mutating one is the client-go
      mutate-a-cached-object programming error);
    - kinds not subscribed fall through to the underlying API — which may
      be a ``ChaosApiServer``, so fault injection still sits *ahead* of
      the cache for everything that actually leaves the informer;
    - freshness: events are enqueued synchronously at write time and
      drained on every read (``sync``), so in-process reads always observe
      their own writes.
    """

    def __init__(self, api: Any):
        self.api = api
        self._watches: Dict[str, Any] = {}     # kind -> watch queue
        self._store: Dict[Tuple[str, str, str], Any] = {}
        self._by_kind: Dict[str, Dict[Tuple[str, str, str], Any]] = {}
        self._by_kind_ns: Dict[Tuple[str, str], Dict[Tuple[str, str, str], Any]] = {}
        self._lock = threading.Lock()

    def watch_kind(self, kind: str) -> None:
        with self._lock:
            if kind in self._watches:
                return
            self._watches[kind] = self.api.watch(kind)

    def caches(self, kind: str) -> bool:
        return kind in self._watches

    def sync(self) -> int:
        """Drain every subscription into the local store; returns events
        applied."""
        n = 0
        with self._lock:
            for q in self._watches.values():
                while not q.empty():
                    ev = q.get()
                    key = _key(ev.object)
                    if ev.type == "DELETED":
                        self._store.pop(key, None)
                        index_drop(self._by_kind, self._by_kind_ns, key)
                    else:
                        self._store[key] = ev.object
                        index_put(self._by_kind, self._by_kind_ns,
                                  key, ev.object)
                    n += 1
        return n

    # -- reads --

    def get(self, kind: str, name: str, namespace: str = "", *,
            copy: bool = True) -> Any:
        if not self.caches(kind):
            return self.api.get(kind, name, namespace, copy=copy)
        self.sync()
        ns = "" if kind in CLUSTER_SCOPED else namespace
        with self._lock:
            obj = self._store.get((kind, ns, name))
        if obj is None:
            raise NotFoundError(f"{kind} {namespace}/{name} not found")
        return _deepcopy(obj) if copy else obj

    def try_get(self, kind: str, name: str, namespace: str = "", *,
                copy: bool = True) -> Optional[Any]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        *,
        copy: bool = True,
    ) -> List[Any]:
        if not self.caches(kind):
            return self.api.list(kind, namespace, label_selector, copy=copy)
        self.sync()
        with self._lock:
            out = list_bucket(self._by_kind, self._by_kind_ns,
                              kind, namespace, label_selector)
        if copy:
            out = [_deepcopy(o) for o in out]
        return _sorted_objs(out)

    def close(self) -> None:
        with self._lock:
            for q in self._watches.values():
                self.api.stop_watch(q)
            self._watches.clear()
            self._store.clear()
            self._by_kind.clear()
            self._by_kind_ns.clear()


class Controller:
    """Base class: subclasses set WATCH_KINDS and implement reconcile(key).

    ``key`` is (namespace, name) of the primary kind (WATCH_KINDS[0]);
    events on secondary kinds are mapped back to the primary via
    ``map_to_primary`` (the reference's Watches+handler.EnqueueRequestsFrom
    MapFunc wiring, notebook_controller.go:512-609).
    """

    NAME = "controller"
    WATCH_KINDS: Tuple[str, ...] = ()

    def __init__(self, api: InMemoryApiServer, registry: MetricsRegistry = global_registry):
        self.api = api
        # Read surface for list/get loops that do NOT mutate-then-update.
        # Defaults to the API itself; ControllerManager.register swaps in
        # its shared CachedReader (informer cache) when the backend
        # supports synchronous watches.
        self.reader: Any = api
        self.log = get_logger(self.NAME)
        # Sanitized interpolation: NAMEs like "fake-kubelet" must not
        # produce exposition-illegal metric names (CI obs-smoke parses
        # the scrape).
        mname = sanitize_metric_name(self.NAME)
        self.metrics_reconcile = registry.counter(
            f"kftpu_{mname}_reconcile_total",
            f"Reconcile outcomes for {self.NAME}",
            labels=("result",),
        )
        self.metrics_retries = registry.counter(
            f"kftpu_{mname}_retries_total",
            f"Requeues after failed reconciles for {self.NAME}",
            labels=("reason",),
        )
        self.heartbeat = registry.heartbeat(self.NAME)

    # -- override points --

    def reconcile(self, namespace: str, name: str) -> Result:
        raise NotImplementedError

    def map_to_primary(self, obj: Any) -> Optional[Tuple[str, str]]:
        """Map a secondary-kind object to the primary key. Default: follow
        the controller ownerReference (by name) or the job/notebook label."""
        for ref in obj.metadata.owner_references:
            if ref.kind == self.WATCH_KINDS[0]:
                return (obj.metadata.namespace, ref.name)
        return None


class ControllerManager:
    """Runs a set of controllers against one API server.

    Two modes:
    - ``run_until_idle()``: deterministic synchronous draining for tests and
      tpuctl --wait (process events → reconcile → repeat until no work,
      honouring due requeues). The analogue of envtest's eventually-
      consistent assertions but without sleeps.
    - ``start()/stop()``: background thread pumping the same loop, for
      long-running services.
    """

    #: Consecutive conflicts on one key retried immediately (the standard
    #: informer dance: re-read, re-apply). Beyond this the key is fighting
    #: another writer — fall back to the exponential limiter so a conflict
    #: storm can't spin the queue hot.
    CONFLICT_IMMEDIATE_RETRIES = 5

    #: Causal links kept per pending key: events that dedup into an
    #: already-queued key append their write span, capped so a hot key
    #: cannot grow an unbounded link list.
    MAX_LINKS_PER_KEY = 4

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        limiter: Optional[ExponentialBackoffLimiter] = None,
        use_cache: Optional[bool] = None,
        tracer: Tracer = global_tracer,
    ):
        self.api = api
        self.tracer = tracer
        self.controllers: List[Controller] = []
        self.limiter = limiter or ExponentialBackoffLimiter()
        self._queues: List[Any] = []
        # Shared informer cache for controller reads. Enabled only when the
        # backend delivers watch events synchronously at write time (the
        # in-memory server, possibly behind a chaos/fault wrapper exposing
        # .inner) — the kubectl backend's poll-based watch would make cache
        # reads lag direct reads, so it keeps reader == api.
        if use_cache is None:
            use_cache = isinstance(
                getattr(api, "inner", api), InMemoryApiServer
            )
        self._cache: Optional[CachedReader] = \
            CachedReader(api) if use_cache else None
        # deque + set mirror: O(1) at both ends — chaos-scale event storms
        # made the old list's membership scans and pop(0) quadratic.
        self._pending: "collections.deque[Tuple[Controller, Tuple[str, str]]]" = \
            collections.deque()
        self._pending_set: set = set()
        # Per-pending-key observability meta (under self._lock, popped at
        # dequeue): first-enqueue monotonic time (queue-wait measurement)
        # and the span contexts of the writes whose events enqueued it
        # (reconcile-span links).
        self._pending_meta: Dict[
            Tuple[Controller, Tuple[str, str]],
            Tuple[float, List[SpanContext]],
        ] = {}
        self._timers: List[Tuple[float, int, Controller, Tuple[str, str]]] = []
        self._timer_seq = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.log = get_logger("manager")
        # Queue-health gauges (client-go workqueue_depth analogues). On a
        # shared registry the first manager's callbacks win, matching the
        # one-manager-per-process deployment shape; the weakref keeps that
        # first-wins registration from pinning a discarded manager alive.
        wref = weakref.ref(self)

        def _of_manager(attr_len: Callable[["ControllerManager"], float]):
            def read() -> float:
                m = wref()
                return attr_len(m) if m is not None else 0.0
            return read

        registry.gauge(
            "kftpu_workqueue_depth",
            "Reconcile keys waiting in the immediate work queue",
            fn=_of_manager(lambda m: float(len(m._pending))),
        )
        registry.gauge(
            "kftpu_workqueue_backoff_pending",
            "Reconcile keys parked on requeue/backoff timers",
            fn=_of_manager(lambda m: float(len(m._timers))),
        )
        registry.gauge(
            "kftpu_workqueue_failing_keys",
            "Keys with a nonzero failure count in the backoff limiter",
            fn=_of_manager(lambda m: float(m.limiter.tracked_keys())),
        )
        # Latency decomposition (ISSUE 4): where a key's end-to-end time
        # goes — write → watch delivery → queue wait → reconcile. Queue
        # wait and watch lag get a wider tail than the verb/reconcile
        # histograms: at fleet scale a key legitimately waits tens of
        # seconds behind thousands of peers, and clamping at 5s would
        # erase exactly the signal this layer exists to expose.
        from kubeflow_tpu.utils.monitoring import DEFAULT_LATENCY_BUCKETS

        wait_buckets = DEFAULT_LATENCY_BUCKETS + (10.0, 30.0, 60.0, 120.0)
        self.metrics_reconcile_latency = registry.histogram(
            "kftpu_reconcile_duration_seconds",
            "Reconcile execution latency",
            labels=("controller", "result"),
        )
        self.metrics_queue_wait = registry.histogram(
            "kftpu_workqueue_wait_seconds",
            "Enqueue-to-dequeue wait in the immediate work queue",
            labels=("controller",),
            buckets=wait_buckets,
        )
        self.metrics_watch_lag = registry.histogram(
            "kftpu_watch_delivery_lag_seconds",
            "Write-to-drain lag of watch events",
            labels=("controller",),
            buckets=wait_buckets,
        )

    def register(self, ctl: Controller) -> None:
        self.controllers.append(ctl)
        for i, kind in enumerate(ctl.WATCH_KINDS):
            q = self.api.watch(kind)
            self._queues.append((ctl, i == 0, q))
            if self._cache is not None:
                self._cache.watch_kind(kind)
        if self._cache is not None:
            ctl.reader = self._cache

    def unregister(self, ctl: Controller) -> None:
        """Release a controller's watch queues and drop its pending work.
        (Registered watches used to leak: a discarded manager's queues kept
        accumulating a copy of every matching event forever.)"""
        with self._lock:
            released = [e[2] for e in self._queues if e[0] is ctl]
            self._queues = [e for e in self._queues if e[0] is not ctl]
            if ctl in self.controllers:
                self.controllers.remove(ctl)
            self._pending = collections.deque(
                (c, k) for c, k in self._pending if c is not ctl
            )
            self._pending_set = {(c, k) for c, k in self._pending_set
                                 if c is not ctl}
            self._pending_meta = {pk: m for pk, m in self._pending_meta.items()
                                  if pk[0] is not ctl}
            self._timers = [t for t in self._timers if t[2] is not ctl]
            heapq.heapify(self._timers)
        ctl.reader = ctl.api
        # stop_watch outside the manager lock: it takes the API server's
        # lock, and no path holds them in the opposite order.
        for q in released:
            self.api.stop_watch(q)

    def close(self) -> None:
        """Tear the manager down: stop the background thread, release every
        registered watch queue and the shared informer cache. Tests and
        benches that build throwaway managers call this so discarded
        managers stop receiving (and buffering) every future event."""
        self.stop()
        for ctl in list(self.controllers):
            self.unregister(ctl)
        if self._cache is not None:
            self._cache.close()

    # ------------- queue pumping -------------

    def _drain_watches(self) -> int:
        n = 0
        now = time.monotonic()
        for ctl, primary, q in self._queues:
            while not q.empty():
                ev = q.get()
                n += 1
                if ev.ts_mono > 0:
                    # Write-time → drain-time lag; under chaos watch-lag
                    # injection this provably includes the injected delay.
                    self.metrics_watch_lag.observe(
                        max(0.0, now - ev.ts_mono), controller=ctl.NAME)
                if primary:
                    key = (ev.object.metadata.namespace, ev.object.metadata.name)
                else:
                    key = ctl.map_to_primary(ev.object)
                if key is not None:
                    self._enqueue(ctl, key, link=ev.span_ctx)
        return n

    def _pending_add_locked(self, ctl: Controller, key: Tuple[str, str],
                            link: Optional[SpanContext] = None) -> None:
        if ctl not in self.controllers:
            # unregister() raced a pump thread still draining the released
            # queue: drop the key instead of reconciling a controller the
            # caller already tore down.
            return
        pkey = (ctl, key)
        if pkey not in self._pending_set:
            self._pending_set.add(pkey)
            self._pending.append(pkey)
            self._pending_meta[pkey] = (
                time.monotonic(), [link] if link is not None else []
            )
        elif link is not None:
            # Deduped into an existing entry: keep the causal link (bounded)
            # so the one reconcile that retires N collapsed events can
            # point back at each triggering write.
            meta = self._pending_meta.get(pkey)
            if meta is not None and len(meta[1]) < self.MAX_LINKS_PER_KEY:
                meta[1].append(link)

    def _enqueue(self, ctl: Controller, key: Tuple[str, str],
                 link: Optional[SpanContext] = None) -> None:
        with self._lock:
            self._pending_add_locked(ctl, key, link)

    def _due_timers(self) -> None:
        now = time.time()
        with self._lock:
            while self._timers and self._timers[0][0] <= now:
                _, _, ctl, key = heapq.heappop(self._timers)
                self._pending_add_locked(ctl, key)

    def _schedule(self, ctl: Controller, key: Tuple[str, str], after: float) -> None:
        with self._lock:
            self._timer_seq += 1
            heapq.heappush(
                self._timers, (time.time() + after, self._timer_seq, ctl, key)
            )

    def _process_one(self) -> bool:
        with self._lock:
            if not self._pending:
                return False
            ctl, key = self._pending.popleft()
            self._pending_set.discard((ctl, key))
            meta = self._pending_meta.pop((ctl, key), None)
        links: List[SpanContext] = []
        if meta is not None:
            self.metrics_queue_wait.observe(
                max(0.0, time.monotonic() - meta[0]), controller=ctl.NAME)
            links = meta[1]
        lkey = (ctl.NAME, key)
        # The reconcile span ADOPTS the trace of the write that enqueued it
        # (first link), so one trace id covers write → watch → reconcile →
        # the status updates made inside (those nest via the contextvar).
        with self.tracer.span(
            "reconcile",
            attrs={"controller": ctl.NAME, "namespace": key[0],
                   "name": key[1]},
            links=links,
            trace_id=links[0][0] if links else None,
        ) as span:
            outcome = "ok"
            try:
                res = ctl.reconcile(*key) or Result()
                ctl.metrics_reconcile.inc(result="ok")
                self.limiter.forget(lkey)
                if res.requeue_after is not None:
                    span.attrs["requeue_after_s"] = res.requeue_after
                    self._schedule(ctl, key, res.requeue_after)
            except ConflictError:
                # Stale read: immediate requeue (re-read, re-apply — the
                # standard informer dance) while the conflicts look
                # transient; a key that keeps losing the write race backs
                # off instead.
                outcome = "conflict"
                ctl.metrics_reconcile.inc(result="conflict")
                ctl.metrics_retries.inc(reason="conflict")
                delay = self.limiter.next_delay(lkey)
                if self.limiter.failures(lkey) <= self.CONFLICT_IMMEDIATE_RETRIES:
                    self._enqueue(ctl, key)
                else:
                    span.attrs["backoff_s"] = delay
                    self._schedule(ctl, key, delay)
            except NotFoundError:
                # A NotFound from arbitrary API calls mid-reconcile is a
                # race (dependent deleted under us, injected fault), not
                # proof the primary is gone — retry with backoff; if the
                # primary really was deleted the next pass exits cleanly
                # via try_get.
                outcome = "gone"
                ctl.metrics_reconcile.inc(result="gone")
                ctl.metrics_retries.inc(reason="not_found")
                delay = self.limiter.next_delay(lkey)
                span.attrs["backoff_s"] = delay
                self._schedule(ctl, key, delay)
            except Exception:
                outcome = "error"
                ctl.metrics_reconcile.inc(result="error")
                ctl.metrics_retries.inc(reason="error")
                ctl.log.error(
                    f"reconcile {key} failed:\n{traceback.format_exc()}"
                )
                delay = self.limiter.next_delay(lkey)
                span.attrs["backoff_s"] = delay
                self._schedule(ctl, key, delay)
            span.attrs["outcome"] = outcome
        self.metrics_reconcile_latency.observe(
            span.duration_s, controller=ctl.NAME, result=outcome)
        ctl.heartbeat.beat()
        return True

    def is_idle(self) -> bool:
        """No queued reconciles and no undrained watch events — used by the
        availability prober: a stale heartbeat is only a wedge when there is
        work waiting."""
        with self._lock:
            if self._pending:
                return False
        return all(q.empty() for _, _, q in self._queues)

    def run_until_idle(self, max_iterations: int = 10000, include_timers_within: float = 0.0) -> int:
        """Drain watches + queue until no immediate work remains. Returns the
        number of reconciles executed. Timers due within
        ``include_timers_within`` seconds are fast-forwarded (lets tests
        exercise requeue-after logic without sleeping)."""
        done = 0
        for _ in range(max_iterations):
            self._drain_watches()
            self._due_timers()
            if include_timers_within > 0:
                with self._lock:
                    while self._timers and (
                        self._timers[0][0] - time.time() <= include_timers_within
                    ):
                        _, _, ctl, key = heapq.heappop(self._timers)
                        self._pending_add_locked(ctl, key)
            if not self._process_one():
                if self._drain_watches() == 0:
                    return done
                continue
            done += 1
        raise RuntimeError(
            f"run_until_idle did not converge in {max_iterations} iterations "
            "(reconcile livelock — controllers keep producing events)"
        )

    # ------------- background mode -------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self._drain_watches()
                self._due_timers()
                if not self._process_one():
                    time.sleep(0.01)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None


# --------------------------------------------------------------------------
# create_or_update: the reconcilehelper equivalent
# --------------------------------------------------------------------------

def create_or_update(
    api: InMemoryApiServer,
    desired: Any,
    *,
    copy_fields: Optional[Callable[[Any, Any], bool]] = None,
) -> Any:
    """Idempotently ensure ``desired`` exists; if present, copy the mutable
    fields onto the live object and update only when something changed
    (components/common/reconcilehelper/util.go:18-107's Deployment/Service/
    VirtualService helpers generalised).

    ``copy_fields(live, desired) -> changed`` defaults to comparing+copying
    ``spec`` plus labels/annotations — the same field set the reference's
    Copy*Fields functions sync.

    The steady-state call is a no-op (idempotent second pass), so for the
    default field set the live object is first read zero-copy and compared
    without mutation; only a detected drift pays the private copy + update.
    A custom ``copy_fields`` mutates its ``live`` argument, so that path
    always reads a private copy.

    The return value is READ-ONLY by contract: on the no-drift fast path
    it is the store's shared snapshot (every other path happens to return
    a private object, but callers must not rely on that). A caller that
    wants to mutate-then-update afterwards re-reads with
    ``api.get(..., copy=True)``.
    """
    if copy_fields is None:
        probe = api.try_get(
            desired.kind, desired.metadata.name, desired.metadata.namespace,
            copy=False,
        )
        if probe is not None and (
            (getattr(desired, "spec", None) is None
             or probe.spec == desired.spec)
            and all(
                {**getattr(probe.metadata, f), **getattr(desired.metadata, f)}
                == getattr(probe.metadata, f)
                for f in ("labels", "annotations")
            )
        ):
            return probe
    # Missing, drifted, or custom copy_fields: read a private copy (the
    # same informer-read fault surface as before) and apply below.
    live = api.try_get(
        desired.kind, desired.metadata.name, desired.metadata.namespace
    )
    if live is None:
        return api.create(desired)

    def default_copy(live_obj: Any, want: Any) -> bool:
        changed = False
        if getattr(want, "spec", None) is not None and live_obj.spec != want.spec:
            live_obj.spec = want.spec
            changed = True
        for field in ("labels", "annotations"):
            want_map = getattr(want.metadata, field)
            live_map = getattr(live_obj.metadata, field)
            merged = {**live_map, **want_map}
            if merged != live_map:
                setattr(live_obj.metadata, field, merged)
                changed = True
        return changed

    fn = copy_fields or default_copy
    if fn(live, desired):
        return api.update(live)
    return live
