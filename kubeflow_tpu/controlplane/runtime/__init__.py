from kubeflow_tpu.controlplane.runtime.apiserver import (
    ApiError,
    ConflictError,
    ContinueExpiredError,
    InMemoryApiServer,
    ListPage,
    NotFoundError,
    WatchEvent,
)
from kubeflow_tpu.controlplane.runtime.ratelimiter import (
    ExponentialBackoffLimiter,
)
from kubeflow_tpu.controlplane.runtime.reconciler import (
    CachedReader,
    Controller,
    ControllerManager,
    Result,
    create_or_update,
)
from kubeflow_tpu.controlplane.runtime.events import EventRecorder

__all__ = [
    "ApiError",
    "ConflictError",
    "ContinueExpiredError",
    "ListPage",
    "ExponentialBackoffLimiter",
    "InMemoryApiServer",
    "NotFoundError",
    "WatchEvent",
    "CachedReader",
    "Controller",
    "ControllerManager",
    "Result",
    "create_or_update",
    "EventRecorder",
]
