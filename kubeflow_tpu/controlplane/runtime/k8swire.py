"""Internal typed objects <-> REAL Kubernetes wire manifests.

The platform's internal API types (controlplane/api/core.py) are a typed,
snake_case model tuned for the controllers — like client-go's typed
structs, they are NOT the wire format. This module is the boundary where
the real Kubernetes (and Istio) API shapes are produced and consumed:

- ``to_wire(obj)``: a manifest a REAL apiserver accepts — containers
  carry ``ports: [{containerPort}]`` and ``resources: {requests,limits}``,
  volumes use ``persistentVolumeClaim/configMap/secret`` objects,
  ``creationTimestamp`` is RFC3339, status uses ``podIP``/``hostIP``
  casing, Istio kinds nest under ``spec``, Events carry
  ``involvedObject`` — every shape checked against the vendored
  structural schemas in ``k8s_schema.py``.
- ``from_wire(data)``: the inverse, tolerant of the extra fields a real
  cluster adds (nodeName, containerStatuses, managedFields, ...).

Reference parity: the reference vendors the k8s OpenAPI spec and talks to
a real apiserver in its controller tests
(bootstrap/k8sSpec/v1.11.7, profile-controller/controllers/suite_test.go:50-72);
here the same fidelity contract is enforced at this adapter + the
schema-validating kubectl fake (tests/fake_kubectl.py).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, Optional

from kubeflow_tpu.controlplane.api.serde import from_dict, to_dict
from kubeflow_tpu.controlplane.api.types import object_from_dict

__all__ = ["to_wire", "from_wire"]

# Annotation keys allow exactly ONE "/" (prefix/name), so hints ride a
# dedicated prefix: scheduler-hints.tpu.kubeflow.org/<hint-key>.
_SCHEDULER_HINTS_ANNO = "scheduler-hints.tpu.kubeflow.org"


def _rfc3339(epoch: float) -> str:
    return _dt.datetime.fromtimestamp(
        epoch, _dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _epoch(stamp: str) -> float:
    return _dt.datetime.strptime(
        stamp, "%Y-%m-%dT%H:%M:%SZ").replace(
        tzinfo=_dt.timezone.utc).timestamp()


def _meta_to_wire(meta: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in meta.items() if v not in ("", 0, 0.0, None, [])}
    rv = out.pop("resourceVersion", None)
    if rv:
        out["resourceVersion"] = str(rv)
    for key in ("creationTimestamp", "deletionTimestamp"):
        ts = out.pop(key, None)
        if ts:
            out[key] = _rfc3339(float(ts))
    return out


def _meta_from_wire(meta: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(meta)
    rv = out.get("resourceVersion")
    if isinstance(rv, str):
        out["resourceVersion"] = int(rv) if rv.isdigit() else 0
    for key in ("creationTimestamp", "deletionTimestamp"):
        ts = out.get(key)
        if isinstance(ts, str):
            try:
                out[key] = _epoch(ts)
            except ValueError:
                out.pop(key)
        elif ts is None and key in out:
            out.pop(key)
    out.pop("managedFields", None)
    return out


def _condition_to_wire(c: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in c.items() if v not in ("", None)}
    ts = out.pop("lastTransitionTime", None)
    if ts:
        out["lastTransitionTime"] = _rfc3339(float(ts))
    return out


def _condition_from_wire(c: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(c)
    ts = out.get("lastTransitionTime")
    if isinstance(ts, str):
        try:
            out["lastTransitionTime"] = _epoch(ts)
        except ValueError:
            out.pop("lastTransitionTime")
    out.pop("lastProbeTime", None)
    return out


# ---------------------------------------------------------------- Pod


def _container_to_wire(c: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in c.items() if v}
    ports = out.pop("ports", None)
    if ports:
        out["ports"] = [{"containerPort": int(p)} for p in ports]
    res = out.pop("resources", None)
    if res:
        # The platform's semantics are guaranteed-capacity scheduling:
        # requests == limits (k8s requires limits for extended resources
        # like google.com/tpu anyway).
        out["resources"] = {"requests": dict(res), "limits": dict(res)}
    env_from = out.pop("envFrom", None)
    if env_from:
        out["envFrom"] = [{"configMapRef": {"name": n}} for n in env_from]
    return out


def _container_from_wire(c: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(c)
    ports = out.get("ports")
    if ports and isinstance(ports[0], dict):
        out["ports"] = [int(p.get("containerPort", 0)) for p in ports]
    res = out.get("resources")
    if isinstance(res, dict) and ("requests" in res or "limits" in res):
        out["resources"] = dict(res.get("limits") or res.get("requests") or {})
    env_from = out.get("envFrom")
    if env_from and isinstance(env_from[0], dict):
        out["envFrom"] = [e.get("configMapRef", {}).get("name", "")
                          for e in env_from]
    for drop in ("terminationMessagePath", "terminationMessagePolicy",
                 "imagePullPolicy", "securityContext", "livenessProbe",
                 "readinessProbe", "startupProbe", "lifecycle", "stdin",
                 "tty", "workingDir", "envFromDownward"):
        out.pop(drop, None)
    return out


def _volume_to_wire(v: Dict[str, Any]) -> Dict[str, Any]:
    out = {"name": v.get("name", "")}
    if v.get("emptyDir") is not None:
        out["emptyDir"] = v["emptyDir"] or {}
    elif v.get("pvc"):
        out["persistentVolumeClaim"] = {"claimName": v["pvc"]}
    elif v.get("configMap"):
        out["configMap"] = {"name": v["configMap"]}
    elif v.get("secret"):
        out["secret"] = {"secretName": v["secret"]}
    else:
        out["emptyDir"] = {}
    return out


def _volume_from_wire(v: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": v.get("name", "")}
    if "emptyDir" in v:
        out["emptyDir"] = v["emptyDir"] or {}
    elif "persistentVolumeClaim" in v:
        out["pvc"] = v["persistentVolumeClaim"].get("claimName", "")
    elif "configMap" in v:
        out["configMap"] = v["configMap"].get("name", "")
    elif "secret" in v:
        out["secret"] = v["secret"].get("secretName", "")
    return out


def _pod_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    spec = d.get("spec", {})
    wire_spec: Dict[str, Any] = {
        "containers": [_container_to_wire(c)
                       for c in spec.get("containers", [])],
    }
    if spec.get("volumes"):
        wire_spec["volumes"] = [_volume_to_wire(v) for v in spec["volumes"]]
    if spec.get("nodeSelector"):
        wire_spec["nodeSelector"] = spec["nodeSelector"]
    if spec.get("serviceAccount"):
        wire_spec["serviceAccountName"] = spec["serviceAccount"]
    if spec.get("restartPolicy"):
        wire_spec["restartPolicy"] = spec["restartPolicy"]
    if spec.get("subdomain"):
        wire_spec["subdomain"] = spec["subdomain"]
    if spec.get("hostname"):
        wire_spec["hostname"] = spec["hostname"]
    meta = _meta_to_wire(d.get("metadata", {}))
    hints = spec.get("schedulerHints")
    if hints:
        # Not a k8s field: ride the standard annotation channel (the way
        # schedulers actually consume placement hints).
        anno = dict(meta.get("annotations", {}))
        anno.update({f"{_SCHEDULER_HINTS_ANNO}/{k}": str(v)
                     for k, v in hints.items()})
        meta["annotations"] = anno
    out = {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
           "spec": wire_spec}
    status = d.get("status") or {}
    if status:
        # A real apiserver IGNORES status on create and takes it via the
        # --subresource=status path — always emit it with real casing so
        # a Pending status (message, conditions) persists too.
        out["status"] = _pod_status_to_wire(status)
    return out


def _pod_status_to_wire(status: Dict[str, Any]) -> Dict[str, Any]:
    ws: Dict[str, Any] = {"phase": status.get("phase", "Pending")}
    if status.get("podIp"):
        ws["podIP"] = status["podIp"]
    if status.get("hostIp"):
        ws["hostIP"] = status["hostIp"]
    if status.get("message"):
        ws["message"] = status["message"]
    if status.get("conditions"):
        ws["conditions"] = [_condition_to_wire(c)
                            for c in status["conditions"]]
    # status.node_name has NO wire channel: on a real cluster the node
    # assignment is spec.nodeName (scheduler-owned, not writable through
    # the status subresource). from_wire maps spec.nodeName back into
    # status.node_name, so reads from a live cluster stay faithful.
    if status.get("terminationMessage"):
        ws["containerStatuses"] = [{
            "name": "main", "ready": False, "restartCount": 0,
            "image": "", "imageID": "",
            "state": {"terminated": {
                "exitCode": 0 if status.get("phase") == "Succeeded" else 1,
                "message": status["terminationMessage"],
            }},
        }]
    return ws


def _pod_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    spec = dict(data.get("spec", {}))
    out_spec: Dict[str, Any] = {
        "containers": [_container_from_wire(c)
                       for c in spec.get("containers", [])],
    }
    if spec.get("volumes"):
        out_spec["volumes"] = [_volume_from_wire(v) for v in spec["volumes"]]
    for src, dst in (("nodeSelector", "nodeSelector"),
                     ("restartPolicy", "restartPolicy"),
                     ("subdomain", "subdomain"),
                     ("hostname", "hostname")):
        if spec.get(src):
            out_spec[dst] = spec[src]
    sa = spec.get("serviceAccountName") or spec.get("serviceAccount")
    if sa:
        out_spec["serviceAccount"] = sa
    meta = _meta_from_wire(data.get("metadata", {}))
    anno = meta.get("annotations") or {}
    hints = {k[len(_SCHEDULER_HINTS_ANNO) + 1:]: v
             for k, v in anno.items()
             if k.startswith(_SCHEDULER_HINTS_ANNO + "/")}
    if hints:
        out_spec["schedulerHints"] = hints
        meta["annotations"] = {
            k: v for k, v in anno.items()
            if not k.startswith(_SCHEDULER_HINTS_ANNO + "/")}
    status = data.get("status") or {}
    out_status: Dict[str, Any] = {}
    if status:
        out_status = {"phase": status.get("phase", "Pending")}
        if status.get("podIP"):
            out_status["podIp"] = status["podIP"]
        if status.get("hostIP"):
            out_status["hostIp"] = status["hostIP"]
        if status.get("message"):
            out_status["message"] = status["message"]
        if status.get("conditions"):
            out_status["conditions"] = [_condition_from_wire(c)
                                        for c in status["conditions"]]
        for cs in status.get("containerStatuses", []):
            msg = (cs.get("state", {}).get("terminated") or {}).get("message")
            if msg:
                out_status["terminationMessage"] = msg
    if spec.get("nodeName"):
        # Real clusters record the node assignment in spec.nodeName; the
        # internal model keeps it on status (kubelet-reported).
        out_status.setdefault("phase", "Pending")
        out_status["nodeName"] = spec["nodeName"]
    out = {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
           "spec": out_spec}
    if out_status:
        out["status"] = out_status
    return out


# ---------------------------------------------------------------- Service


def _service_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    spec = d.get("spec", {})
    wire_spec: Dict[str, Any] = {}
    if spec.get("selector"):
        wire_spec["selector"] = spec["selector"]
    ports = []
    for p in spec.get("ports", []):
        wp: Dict[str, Any] = {"port": int(p.get("port", 0))}
        if p.get("name"):
            wp["name"] = p["name"]
        if p.get("targetPort"):
            wp["targetPort"] = int(p["targetPort"])
        ports.append(wp)
    if ports:
        wire_spec["ports"] = ports
    if spec.get("clusterIp"):
        wire_spec["clusterIP"] = spec["clusterIp"]
    if spec.get("type") and spec["type"] != "ClusterIP":
        wire_spec["type"] = spec["type"]
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "spec": wire_spec}


def _service_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    spec = dict(data.get("spec", {}))
    out_spec: Dict[str, Any] = {}
    if spec.get("selector"):
        out_spec["selector"] = spec["selector"]
    ports = []
    for p in spec.get("ports", []):
        tp = p.get("targetPort", 0)
        ports.append({"name": p.get("name", ""),
                      "port": int(p.get("port", 0)),
                      "targetPort": int(tp) if isinstance(
                          tp, (int, float)) else 0})
    if ports:
        out_spec["ports"] = ports
    if spec.get("clusterIP"):
        out_spec["clusterIp"] = spec["clusterIP"]
    if spec.get("type"):
        out_spec["type"] = spec["type"]
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "spec": out_spec}


# ---------------------------------------------------------------- Istio


def _virtualservice_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    http = []
    for r in d.get("http", []):
        route: Dict[str, Any] = {
            "match": [{"uri": {"prefix": r.get("prefix", "/")}}],
            "route": [{"destination": {
                "host": r.get("destinationHost", ""),
                "port": {"number": int(r.get("destinationPort", 0))},
            }}],
        }
        if r.get("rewrite"):
            route["rewrite"] = {"uri": r["rewrite"]}
        http.append(route)
    return {"apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "spec": {"gateways": d.get("gateways", []),
                     "hosts": d.get("hosts", []),
                     "http": http}}


def _virtualservice_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    spec = data.get("spec", {})
    http = []
    for r in spec.get("http", []):
        match = (r.get("match") or [{}])[0]
        dest = (r.get("route") or [{}])[0].get("destination", {})
        http.append({
            "prefix": match.get("uri", {}).get("prefix", ""),
            "rewrite": (r.get("rewrite") or {}).get("uri", ""),
            "destinationHost": dest.get("host", ""),
            "destinationPort": dest.get("port", {}).get("number", 0),
        })
    return {"apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "gateways": spec.get("gateways", []),
            "hosts": spec.get("hosts", []),
            "http": http}


def _authorizationpolicy_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    header = d.get("userIdHeader", "x-goog-authenticated-user-email")
    spec: Dict[str, Any] = {"action": d.get("action", "ALLOW")}
    principals = d.get("principals", [])
    spec["rules"] = [{
        "when": [{"key": f"request.headers[{header}]",
                  "values": list(principals)}],
    }] if principals else []
    return {"apiVersion": "security.istio.io/v1",
            "kind": "AuthorizationPolicy",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "spec": spec}


def _authorizationpolicy_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    spec = data.get("spec", {})
    principals = []
    header = "x-goog-authenticated-user-email"
    for rule in spec.get("rules", []):
        for cond in rule.get("when", []):
            key = cond.get("key", "")
            if key.startswith("request.headers[") and key.endswith("]"):
                header = key[len("request.headers["):-1]
                principals.extend(cond.get("values", []))
    return {"apiVersion": "security.istio.io/v1",
            "kind": "AuthorizationPolicy",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "action": spec.get("action", "ALLOW"),
            "principals": principals,
            "userIdHeader": header}


# ---------------------------------------------------------------- Event


def _event_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "Event",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "involvedObject": {
                "kind": d.get("involvedKind", ""),
                "name": d.get("involvedName", ""),
                "namespace": d.get("involvedNamespace", ""),
            },
            "type": d.get("type", "Normal"),
            "reason": d.get("reason", ""),
            "message": d.get("message", ""),
            "count": int(d.get("count", 1))}


def _event_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    inv = data.get("involvedObject", {})
    return {"apiVersion": "v1", "kind": "Event",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "involvedKind": inv.get("kind", ""),
            "involvedName": inv.get("name", ""),
            "involvedNamespace": inv.get("namespace", ""),
            "type": data.get("type", "Normal"),
            "reason": data.get("reason", ""),
            "message": data.get("message", ""),
            "count": int(data.get("count", 1))}


# ---------------------------------------------------------------- simple


def _rolebinding_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    ns = d.get("metadata", {}).get("namespace", "")
    return {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": d.get("roleRef", {}).get(
                            "kind", "ClusterRole"),
                        "name": d.get("roleRef", {}).get("name", "")},
            "subjects": [
                {"apiGroup": "rbac.authorization.k8s.io",
                 "kind": s.get("kind", "User"),
                 "name": s.get("name", "")}
                if s.get("kind", "User") != "ServiceAccount" else
                {"kind": "ServiceAccount", "name": s.get("name", ""),
                 "namespace": ns}
                for s in d.get("subjects", [])
            ]}


def _rolebinding_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    return {"apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "roleRef": {"kind": data.get("roleRef", {}).get(
                "kind", "ClusterRole"),
                "name": data.get("roleRef", {}).get("name", "")},
            "subjects": [{"kind": s.get("kind", "User"),
                          "name": s.get("name", "")}
                         for s in data.get("subjects", [])]}


def _resourcequota_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": _meta_to_wire(d.get("metadata", {})),
            "spec": {"hard": dict(d.get("hard", {}))}}


def _resourcequota_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    return {"apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": _meta_from_wire(data.get("metadata", {})),
            "hard": dict(data.get("spec", {}).get("hard", {}))}


def _passthrough_to_wire(d: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(d)
    out["metadata"] = _meta_to_wire(d.get("metadata", {}))
    return out


def _passthrough_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(data)
    out["metadata"] = _meta_from_wire(data.get("metadata", {}))
    return out


_TO_WIRE = {
    "Pod": _pod_to_wire,
    "Service": _service_to_wire,
    "VirtualService": _virtualservice_to_wire,
    "AuthorizationPolicy": _authorizationpolicy_to_wire,
    "Event": _event_to_wire,
    "RoleBinding": _rolebinding_to_wire,
    "ResourceQuota": _resourcequota_to_wire,
}

_FROM_WIRE = {
    "Pod": _pod_from_wire,
    "Service": _service_from_wire,
    "VirtualService": _virtualservice_from_wire,
    "AuthorizationPolicy": _authorizationpolicy_from_wire,
    "Event": _event_from_wire,
    "RoleBinding": _rolebinding_from_wire,
    "ResourceQuota": _resourcequota_from_wire,
}


def to_wire(obj: Any) -> Dict[str, Any]:
    """Typed internal object -> the manifest a real apiserver accepts."""
    d = to_dict(obj)
    kind = d.get("kind", "")
    fn = _TO_WIRE.get(kind)
    return fn(d) if fn else _passthrough_to_wire(d)


def from_wire(data: Dict[str, Any], kind: str = "") -> Any:
    """Wire manifest -> typed internal object (tolerant of the extra
    server-populated fields a real cluster adds)."""
    if kind:
        data.setdefault("kind", kind)
    k = data.get("kind", "")
    fn = _FROM_WIRE.get(k)
    return object_from_dict(fn(data) if fn else
                            _passthrough_from_wire(data))
