"""Vendored Kubernetes structural schemas + validator.

The reference vendors the full k8s OpenAPI spec so everything it emits is
checked against the real API schema (reference bootstrap/k8sSpec/v1.11.7/
— used by its kfctl apply path), and its controllers run against a real
etcd+apiserver (profile-controller/controllers/suite_test.go:50-72). This
environment has no cluster and no egress, so the equivalent contract is
vendored by hand: STRUCTURAL schemas — the same subset the k8s apiserver
enforces for CRDs (types, required fields, unknown-field pruning) — for
every kind the platform emits, transcribed from the upstream API
definitions (k8s core/v1, apps/v1, rbac/v1, apiextensions/v1 at v1.29;
Istio networking/v1beta1 + security/v1).

``validate(doc)`` returns a list of errors (empty = valid): unknown
fields under typed sections, wrong JSON types, missing required fields,
malformed DNS-1123 names and label keys/values — the error classes a real
apiserver's create would reject and a mirror-image fake parser would
happily accept. Wired into:

- the kubectl adapter's outgoing manifests (runtime/kubectl.py raises
  before exec'ing kubectl with an invalid manifest),
- the kubectl test double (tests/fake_kubectl.py rejects invalid incoming
  objects apiserver-style),
- the release-manifest test tier (tools/release.py emissions).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

__all__ = ["validate", "validate_metadata", "schema_for", "SCHEMAS"]

_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_RFC1035_LABEL = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")

# metadata.name rules differ by resource on a real apiserver: Services
# are RFC1035 labels (DNS A-record hosts), Namespaces DNS-1123 labels,
# RBAC kinds allow path-segment names (e.g. "system:controller:x" — the
# reference's kfam emits "namespaceAdmin"), most others DNS-1123
# subdomains. Keyed by kind; "path-segment" = anything without "/" or
# "%", not "." / "..".
_NAME_RULES = {
    "Service": ("RFC-1035 label", _RFC1035_LABEL),
    "Namespace": ("DNS-1123 label", _DNS1123_LABEL),
    "Role": ("path segment", None),
    "ClusterRole": ("path segment", None),
    "RoleBinding": ("path segment", None),
    "ClusterRoleBinding": ("path segment", None),
}
_LABEL_VALUE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
_QUALIFIED_NAME = re.compile(
    r"^([a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*/)?"
    r"[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_QUANTITY = re.compile(
    r"^[+-]?(\d+|\d+\.\d*|\.\d+)([eE][+-]?\d+|[kKMGTPE]i?|[mun])?$")

# -------------------------------------------------------------- DSL
# str_ / int_ / num / boolean: scalars. obj(props, required=[...],
# open=True) allows unknown props; map_of(v): string-keyed map; arr(item);
# enum(...); int_or_str; quantity (k8s resource.Quantity string); any_.

str_ = {"type": "string"}
int_ = {"type": "integer"}
num = {"type": "number"}
boolean = {"type": "boolean"}
int_or_str = {"type": "int-or-string"}
quantity = {"type": "quantity"}
any_ = {"type": "any"}


def obj(props: Dict[str, Any], required: Optional[List[str]] = None,
        open: bool = False) -> Dict[str, Any]:
    return {"type": "object", "properties": props,
            "required": required or [], "open": open}


def map_of(value_schema: Dict[str, Any]) -> Dict[str, Any]:
    return {"type": "map", "values": value_schema}


def arr(item: Dict[str, Any]) -> Dict[str, Any]:
    return {"type": "array", "items": item}


def enum(*values: str) -> Dict[str, Any]:
    return {"type": "string", "enum": list(values)}


# -------------------------------------------------------------- shared

_OWNER_REF = obj({
    "apiVersion": str_, "kind": str_, "name": str_, "uid": str_,
    "controller": boolean, "blockOwnerDeletion": boolean,
}, required=["apiVersion", "kind", "name", "uid"])

METADATA = obj({
    "name": str_, "namespace": str_, "generateName": str_,
    "labels": map_of(str_), "annotations": map_of(str_),
    "uid": str_, "resourceVersion": str_, "generation": int_,
    "creationTimestamp": str_, "deletionTimestamp": str_,
    "ownerReferences": arr(_OWNER_REF), "finalizers": arr(str_),
    "managedFields": arr(any_), "selfLink": str_,
    "deletionGracePeriodSeconds": int_,
}, required=["name"])

_LABEL_SELECTOR = obj({
    "matchLabels": map_of(str_),
    "matchExpressions": arr(obj({
        "key": str_, "operator": str_, "values": arr(str_),
    }, required=["key", "operator"])),
})

# -------------------------------------------------------------- core/v1

_ENV_VAR = obj({
    "name": str_, "value": str_,
    "valueFrom": obj({
        "fieldRef": obj({"fieldPath": str_, "apiVersion": str_},
                        required=["fieldPath"]),
        "configMapKeyRef": obj({"name": str_, "key": str_, "optional":
                                boolean}, required=["key"]),
        "secretKeyRef": obj({"name": str_, "key": str_, "optional":
                             boolean}, required=["key"]),
        "resourceFieldRef": obj({"resource": str_, "containerName": str_,
                                 "divisor": quantity},
                                required=["resource"]),
    }),
}, required=["name"])

_CONTAINER = obj({
    "name": str_, "image": str_,
    "command": arr(str_), "args": arr(str_),
    "env": arr(_ENV_VAR),
    "envFrom": arr(obj({
        "configMapRef": obj({"name": str_, "optional": boolean}),
        "secretRef": obj({"name": str_, "optional": boolean}),
        "prefix": str_,
    })),
    "ports": arr(obj({
        "containerPort": int_, "name": str_, "protocol":
        enum("TCP", "UDP", "SCTP"), "hostPort": int_, "hostIP": str_,
    }, required=["containerPort"])),
    "resources": obj({
        "requests": map_of(quantity), "limits": map_of(quantity),
        "claims": arr(any_),
    }),
    "volumeMounts": arr(obj({
        "name": str_, "mountPath": str_, "readOnly": boolean,
        "subPath": str_, "mountPropagation": str_,
    }, required=["name", "mountPath"])),
    "workingDir": str_, "imagePullPolicy":
    enum("Always", "IfNotPresent", "Never"),
    "securityContext": any_, "livenessProbe": any_,
    "readinessProbe": any_, "startupProbe": any_, "lifecycle": any_,
    "terminationMessagePath": str_, "terminationMessagePolicy": str_,
    "stdin": boolean, "tty": boolean,
    # Upstream requires only "name" (image may be injected by admission).
}, required=["name"])

_VOLUME = obj({
    "name": str_,
    "emptyDir": obj({"medium": str_, "sizeLimit": quantity}),
    "persistentVolumeClaim": obj({"claimName": str_, "readOnly": boolean},
                                 required=["claimName"]),
    "configMap": obj({"name": str_, "items": arr(any_), "optional":
                      boolean, "defaultMode": int_}),
    "secret": obj({"secretName": str_, "items": arr(any_), "optional":
                   boolean, "defaultMode": int_}),
    "hostPath": obj({"path": str_, "type": str_}, required=["path"]),
    "downwardAPI": any_, "projected": any_,
}, required=["name"])

_POD_SPEC = obj({
    "containers": arr(_CONTAINER),
    "initContainers": arr(_CONTAINER),
    "volumes": arr(_VOLUME),
    "nodeSelector": map_of(str_),
    "serviceAccountName": str_, "serviceAccount": str_,
    "restartPolicy": enum("Always", "OnFailure", "Never"),
    "subdomain": str_, "hostname": str_, "nodeName": str_,
    "schedulerName": str_, "priorityClassName": str_, "priority": int_,
    "terminationGracePeriodSeconds": int_, "activeDeadlineSeconds": int_,
    "dnsPolicy": str_, "hostNetwork": boolean, "tolerations": arr(any_),
    "affinity": any_, "topologySpreadConstraints": arr(any_),
    "imagePullSecrets": arr(obj({"name": str_})),
    "securityContext": any_, "enableServiceLinks": boolean,
    "automountServiceAccountToken": boolean,
}, required=["containers"])

_POD_STATUS = obj({
    "phase": enum("Pending", "Running", "Succeeded", "Failed", "Unknown"),
    "podIP": str_, "hostIP": str_, "message": str_, "reason": str_,
    "conditions": arr(obj({
        "type": str_, "status": str_, "reason": str_, "message": str_,
        "lastTransitionTime": str_, "lastProbeTime": str_,
    }, required=["type", "status"])),
    "containerStatuses": arr(obj({
        "name": str_, "ready": boolean, "restartCount": int_,
        "image": str_, "imageID": str_, "state": any_, "lastState": any_,
        "started": boolean, "containerID": str_,
    }, required=["name"])),
    "podIPs": arr(obj({"ip": str_})), "startTime": str_,
    "qosClass": str_, "initContainerStatuses": arr(any_),
})

POD = obj({
    "apiVersion": enum("v1"), "kind": enum("Pod"),
    "metadata": METADATA, "spec": _POD_SPEC, "status": _POD_STATUS,
}, required=["apiVersion", "kind", "metadata", "spec"])

SERVICE = obj({
    "apiVersion": enum("v1"), "kind": enum("Service"),
    "metadata": METADATA,
    "spec": obj({
        "selector": map_of(str_),
        "ports": arr(obj({
            "name": str_, "port": int_, "targetPort": int_or_str,
            "protocol": enum("TCP", "UDP", "SCTP"), "nodePort": int_,
            "appProtocol": str_,
        }, required=["port"])),
        "clusterIP": str_, "clusterIPs": arr(str_),
        "type": enum("ClusterIP", "NodePort", "LoadBalancer",
                     "ExternalName"),
        "externalName": str_, "sessionAffinity": str_,
        "ipFamilies": arr(str_), "ipFamilyPolicy": str_,
        "internalTrafficPolicy": str_, "externalTrafficPolicy": str_,
    }),
    "status": any_,
}, required=["apiVersion", "kind", "metadata", "spec"])

NAMESPACE = obj({
    "apiVersion": enum("v1"), "kind": enum("Namespace"),
    "metadata": METADATA,
    "spec": obj({"finalizers": arr(str_)}),
    "status": obj({"phase": enum("Active", "Terminating"),
                   "conditions": arr(any_)}, open=True),
}, required=["apiVersion", "kind", "metadata"])

SERVICE_ACCOUNT = obj({
    "apiVersion": enum("v1"), "kind": enum("ServiceAccount"),
    "metadata": METADATA,
    "secrets": arr(obj({"name": str_}, open=True)),
    "imagePullSecrets": arr(obj({"name": str_})),
    "automountServiceAccountToken": boolean,
}, required=["apiVersion", "kind", "metadata"])

RESOURCE_QUOTA = obj({
    "apiVersion": enum("v1"), "kind": enum("ResourceQuota"),
    "metadata": METADATA,
    "spec": obj({"hard": map_of(quantity), "scopes": arr(str_),
                 "scopeSelector": any_}),
    "status": obj({"hard": map_of(quantity), "used": map_of(quantity)}),
}, required=["apiVersion", "kind", "metadata", "spec"])

EVENT = obj({
    "apiVersion": enum("v1"), "kind": enum("Event"),
    "metadata": METADATA,
    "involvedObject": obj({
        "kind": str_, "name": str_, "namespace": str_, "uid": str_,
        "apiVersion": str_, "resourceVersion": str_, "fieldPath": str_,
    }),
    "type": enum("Normal", "Warning"),
    "reason": str_, "message": str_, "count": int_,
    "firstTimestamp": str_, "lastTimestamp": str_, "eventTime": str_,
    "source": obj({"component": str_, "host": str_}),
    "reportingComponent": str_, "reportingInstance": str_,
    "action": str_, "related": any_, "series": any_,
}, required=["apiVersion", "kind", "metadata", "involvedObject"])

SECRET = obj({
    "apiVersion": enum("v1"), "kind": enum("Secret"),
    "metadata": METADATA,
    "type": str_, "data": map_of(str_), "stringData": map_of(str_),
    "immutable": boolean,
}, required=["apiVersion", "kind", "metadata"])

CONFIG_MAP = obj({
    "apiVersion": enum("v1"), "kind": enum("ConfigMap"),
    "metadata": METADATA,
    "data": map_of(str_), "binaryData": map_of(str_),
    "immutable": boolean,
}, required=["apiVersion", "kind", "metadata"])

# -------------------------------------------------------------- rbac/v1

_POLICY_RULE = obj({
    "apiGroups": arr(str_), "resources": arr(str_), "verbs": arr(str_),
    "resourceNames": arr(str_), "nonResourceURLs": arr(str_),
}, required=["verbs"])

_SUBJECT = obj({
    "kind": enum("User", "Group", "ServiceAccount"),
    "name": str_, "namespace": str_, "apiGroup": str_,
}, required=["kind", "name"])

_ROLE_REF = obj({
    "apiGroup": enum("rbac.authorization.k8s.io"),
    "kind": enum("Role", "ClusterRole"), "name": str_,
}, required=["apiGroup", "kind", "name"])


def _rbac(kind: str, namespaced_rules: bool) -> Dict[str, Any]:
    props: Dict[str, Any] = {
        "apiVersion": enum("rbac.authorization.k8s.io/v1"),
        "kind": enum(kind), "metadata": METADATA,
    }
    req = ["apiVersion", "kind", "metadata"]
    if kind.endswith("Binding"):
        props["roleRef"] = _ROLE_REF
        props["subjects"] = arr(_SUBJECT)
        req.append("roleRef")
    else:
        props["rules"] = arr(_POLICY_RULE)
        if kind == "ClusterRole":
            props["aggregationRule"] = any_
    return obj(props, required=req)


ROLE = _rbac("Role", True)
CLUSTER_ROLE = _rbac("ClusterRole", False)
ROLE_BINDING = _rbac("RoleBinding", True)
CLUSTER_ROLE_BINDING = _rbac("ClusterRoleBinding", False)

# -------------------------------------------------------------- apps/v1

DEPLOYMENT = obj({
    "apiVersion": enum("apps/v1"), "kind": enum("Deployment"),
    "metadata": METADATA,
    "spec": obj({
        "replicas": int_,
        "selector": _LABEL_SELECTOR,
        "template": obj({
            "metadata": obj({
                "labels": map_of(str_), "annotations": map_of(str_),
                "name": str_,
            }),
            "spec": _POD_SPEC,
        }, required=["spec"]),
        "strategy": any_, "minReadySeconds": int_,
        "revisionHistoryLimit": int_, "progressDeadlineSeconds": int_,
        "paused": boolean,
    }, required=["selector", "template"]),
    "status": any_,
}, required=["apiVersion", "kind", "metadata", "spec"])

# ------------------------------------------------- apiextensions/v1 CRD

_CRD_VERSION = obj({
    "name": str_, "served": boolean, "storage": boolean,
    "schema": obj({"openAPIV3Schema": any_}),
    "subresources": obj({"status": obj({}), "scale": any_}),
    "additionalPrinterColumns": arr(any_),
    "deprecated": boolean, "deprecationWarning": str_,
}, required=["name", "served", "storage"])

CRD = obj({
    "apiVersion": enum("apiextensions.k8s.io/v1"),
    "kind": enum("CustomResourceDefinition"),
    "metadata": METADATA,
    "spec": obj({
        "group": str_,
        "names": obj({
            "plural": str_, "singular": str_, "kind": str_,
            "listKind": str_, "shortNames": arr(str_),
            "categories": arr(str_),
        }, required=["plural", "kind"]),
        "scope": enum("Namespaced", "Cluster"),
        "versions": arr(_CRD_VERSION),
        "conversion": any_, "preserveUnknownFields": boolean,
    }, required=["group", "names", "scope", "versions"]),
    "status": any_,
}, required=["apiVersion", "kind", "metadata", "spec"])

# -------------------------------------------------------------- istio

VIRTUAL_SERVICE = obj({
    "apiVersion": enum("networking.istio.io/v1beta1",
                       "networking.istio.io/v1alpha3",
                       "networking.istio.io/v1"),
    "kind": enum("VirtualService"),
    "metadata": METADATA,
    "spec": obj({
        "hosts": arr(str_), "gateways": arr(str_),
        "http": arr(obj({
            "match": arr(obj({
                "uri": obj({"prefix": str_, "exact": str_, "regex": str_}),
                "headers": any_, "method": any_, "port": int_,
            })),
            "route": arr(obj({
                "destination": obj({
                    "host": str_,
                    "port": obj({"number": int_}, required=["number"]),
                    "subset": str_,
                }, required=["host"]),
                "weight": int_, "headers": any_,
            }, required=["destination"])),
            "rewrite": obj({"uri": str_, "authority": str_}),
            "redirect": any_, "timeout": str_, "retries": any_,
            "headers": any_, "name": str_,
        })),
        "tcp": arr(any_), "tls": arr(any_), "exportTo": arr(str_),
    }, required=["hosts"]),
}, required=["apiVersion", "kind", "metadata", "spec"])

AUTHORIZATION_POLICY = obj({
    "apiVersion": enum("security.istio.io/v1",
                       "security.istio.io/v1beta1"),
    "kind": enum("AuthorizationPolicy"),
    "metadata": METADATA,
    "spec": obj({
        "action": enum("ALLOW", "DENY", "AUDIT", "CUSTOM"),
        "rules": arr(obj({
            "from": arr(obj({"source": any_})),
            "to": arr(obj({"operation": any_})),
            "when": arr(obj({
                "key": str_, "values": arr(str_),
                "notValues": arr(str_),
            }, required=["key"])),
        })),
        "selector": obj({"matchLabels": map_of(str_)}),
        "provider": any_,
    }),
}, required=["apiVersion", "kind", "metadata", "spec"])

# ------------------------------------------- platform CRs (own group)

_CR_GROUP = "tpu.kubeflow.org"

# Platform CRs: structural at the envelope (the CRD is installed with
# x-kubernetes-preserve-unknown-fields, our serde owns spec validation),
# strict at metadata — exactly what a real apiserver enforces for them.
PLATFORM_CR = obj({
    "apiVersion": str_, "kind": str_, "metadata": METADATA,
    "spec": any_, "status": any_,
}, required=["apiVersion", "kind", "metadata"])


SCHEMAS: Dict[str, Dict[str, Any]] = {
    "v1/Pod": POD,
    "v1/Service": SERVICE,
    "v1/Namespace": NAMESPACE,
    "v1/ServiceAccount": SERVICE_ACCOUNT,
    "v1/ResourceQuota": RESOURCE_QUOTA,
    "v1/Event": EVENT,
    "v1/Secret": SECRET,
    "v1/ConfigMap": CONFIG_MAP,
    "rbac.authorization.k8s.io/v1/Role": ROLE,
    "rbac.authorization.k8s.io/v1/ClusterRole": CLUSTER_ROLE,
    "rbac.authorization.k8s.io/v1/RoleBinding": ROLE_BINDING,
    "rbac.authorization.k8s.io/v1/ClusterRoleBinding": CLUSTER_ROLE_BINDING,
    "apps/v1/Deployment": DEPLOYMENT,
    "apiextensions.k8s.io/v1/CustomResourceDefinition": CRD,
    "networking.istio.io/v1beta1/VirtualService": VIRTUAL_SERVICE,
    "networking.istio.io/v1alpha3/VirtualService": VIRTUAL_SERVICE,
    "networking.istio.io/v1/VirtualService": VIRTUAL_SERVICE,
    "security.istio.io/v1/AuthorizationPolicy": AUTHORIZATION_POLICY,
    "security.istio.io/v1beta1/AuthorizationPolicy": AUTHORIZATION_POLICY,
}


def schema_for(doc: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    api_version = doc.get("apiVersion", "")
    kind = doc.get("kind", "")
    key = f"{api_version}/{kind}"
    if key in SCHEMAS:
        return SCHEMAS[key]
    if api_version.startswith(_CR_GROUP + "/"):
        return PLATFORM_CR
    return None


# -------------------------------------------------------------- validator


def _type_name(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, int):
        return "integer"
    if isinstance(v, float):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, list):
        return "array"
    if isinstance(v, dict):
        return "object"
    if v is None:
        return "null"
    return type(v).__name__


def _walk(schema: Dict[str, Any], value: Any, path: str,
          errors: List[str]) -> None:
    stype = schema.get("type", "any")
    if stype == "any":
        return
    if stype == "string":
        if not isinstance(value, str):
            errors.append(f"{path}: expected string, got "
                          f"{_type_name(value)}")
            return
        allowed = schema.get("enum")
        if allowed and value not in allowed:
            errors.append(f"{path}: {value!r} not in {allowed}")
        return
    if stype == "integer":
        if isinstance(value, bool) or not isinstance(value, int):
            errors.append(f"{path}: expected integer, got "
                          f"{_type_name(value)}")
        return
    if stype == "number":
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            errors.append(f"{path}: expected number, got "
                          f"{_type_name(value)}")
        return
    if stype == "boolean":
        if not isinstance(value, bool):
            errors.append(f"{path}: expected boolean, got "
                          f"{_type_name(value)}")
        return
    if stype == "int-or-string":
        if isinstance(value, bool) or not isinstance(value, (int, str)):
            errors.append(f"{path}: expected int-or-string, got "
                          f"{_type_name(value)}")
        return
    if stype == "quantity":
        if isinstance(value, bool) or not isinstance(value, (int, float,
                                                             str)):
            errors.append(f"{path}: expected quantity, got "
                          f"{_type_name(value)}")
            return
        if isinstance(value, str) and not _QUANTITY.match(value):
            errors.append(f"{path}: {value!r} is not a valid quantity")
        return
    if stype == "map":
        if not isinstance(value, dict):
            errors.append(f"{path}: expected object, got "
                          f"{_type_name(value)}")
            return
        for k, v in value.items():
            if not isinstance(k, str):
                errors.append(f"{path}: non-string key {k!r}")
                continue
            _walk(schema["values"], v, f"{path}.{k}", errors)
        return
    if stype == "array":
        if not isinstance(value, list):
            errors.append(f"{path}: expected array, got "
                          f"{_type_name(value)}")
            return
        for i, item in enumerate(value):
            _walk(schema["items"], item, f"{path}[{i}]", errors)
        return
    if stype == "object":
        if not isinstance(value, dict):
            errors.append(f"{path}: expected object, got "
                          f"{_type_name(value)}")
            return
        props = schema.get("properties", {})
        for req in schema.get("required", []):
            if req not in value:
                errors.append(f"{path}: missing required field {req!r}")
        for k, v in value.items():
            if k in props:
                _walk(props[k], v, f"{path}.{k}", errors)
            elif not schema.get("open", False):
                errors.append(f"{path}: unknown field {k!r}")
        return
    raise AssertionError(f"bad schema node type {stype!r}")


def validate_metadata(meta: Dict[str, Any], path: str = "metadata",
                      errors: Optional[List[str]] = None,
                      kind: str = "") -> List[str]:
    """Name/label syntax — the validation layer beyond structure that a
    real apiserver applies (per-kind name rules, qualified label keys,
    label value charset)."""
    errors = errors if errors is not None else []
    name = meta.get("name", "")
    rule_name, rule_re = _NAME_RULES.get(
        kind, ("DNS-1123 subdomain", _DNS1123_SUBDOMAIN))
    if name:
        if rule_re is None:  # path segment
            if "/" in name or "%" in name or name in (".", ".."):
                errors.append(f"{path}.name: {name!r} is not a valid "
                              f"{rule_name}")
        elif not rule_re.match(name):
            errors.append(f"{path}.name: {name!r} is not a {rule_name}")
    ns = meta.get("namespace", "")
    if ns and not _DNS1123_SUBDOMAIN.match(ns):
        errors.append(
            f"{path}.namespace: {ns!r} is not a DNS-1123 subdomain")
    for k, v in (meta.get("labels") or {}).items():
        if not _QUALIFIED_NAME.match(k):
            errors.append(f"{path}.labels: bad key {k!r}")
        if not isinstance(v, str) or not _LABEL_VALUE.match(v):
            errors.append(f"{path}.labels[{k}]: bad value {v!r}")
    for k in (meta.get("annotations") or {}):
        if not _QUALIFIED_NAME.match(k):
            errors.append(f"{path}.annotations: bad key {k!r}")
    return errors


def validate(doc: Dict[str, Any]) -> List[str]:
    """Validate one wire manifest. Returns error strings (empty = valid).
    Unknown (apiVersion, kind) pairs are themselves an error — a real
    apiserver rejects resources it has no registered type for."""
    if not isinstance(doc, dict):
        return [f"manifest must be an object, got {_type_name(doc)}"]
    schema = schema_for(doc)
    if schema is None:
        return [f"no vendored schema for "
                f"{doc.get('apiVersion', '?')}/{doc.get('kind', '?')} — "
                "register it in k8s_schema.SCHEMAS"]
    errors: List[str] = []
    _walk(schema, doc, doc.get("kind", "?"), errors)
    meta = doc.get("metadata")
    if isinstance(meta, dict):
        validate_metadata(meta, f"{doc.get('kind', '?')}.metadata", errors,
                          kind=doc.get("kind", ""))
    return errors
