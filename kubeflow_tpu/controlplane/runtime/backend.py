"""Shared backend selection for long-lived platform processes.

controlplane.main (controller manager) and webapps.frontend (hub) both
run against either the in-memory dev apiserver or a real cluster through
the kubectl adapter; the flag surface and construction live here once so
new backend options don't drift between entrypoints.
"""

from __future__ import annotations

import argparse

from kubeflow_tpu.controlplane.runtime.apiserver import InMemoryApiServer


def add_backend_args(p: argparse.ArgumentParser,
                     *, default: str = "kubectl") -> None:
    p.add_argument("--backend", choices=("memory", "kubectl"),
                   default=default)
    p.add_argument("--kubectl-bin", default="kubectl")
    p.add_argument("--context", default="")
    p.add_argument("--poll-interval", type=float, default=2.0)


def build_backend(args):
    if args.backend == "kubectl":
        from kubeflow_tpu.controlplane.runtime.kubectl import KubectlApiServer

        return KubectlApiServer(
            kubectl=args.kubectl_bin, context=args.context,
            poll_interval=getattr(args, "poll_interval", 2.0),
        )
    return InMemoryApiServer()


def serve_forever(*cleanups) -> None:
    """Block until interrupted, then run cleanups in order."""
    import time

    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        for fn in cleanups:
            try:
                fn()
            except Exception:  # noqa: BLE001 — best-effort shutdown
                pass
