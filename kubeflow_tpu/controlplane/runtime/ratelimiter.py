"""Per-key exponential backoff for the controller workqueue.

The reference gets this for free from client-go's
``workqueue.DefaultControllerRateLimiter`` (ItemExponentialFailureRateLimiter:
5ms base doubling to a cap, reset on Forget). Our reconciler kernel used a
flat 1.0s requeue for every error, which is both too slow for the first
retry and too hot for a persistently failing object. This module rebuilds
the per-key limiter with two deliberate differences:

- **Deterministic jitter**: delays are decorrelated with a seeded RNG so a
  gang of keys failing together (slice preemption taking out a whole
  fleet) doesn't retry in lockstep, while chaos tests stay reproducible.
  Jitter only ever *shrinks* a delay (factor in ``[1 - jitter, 1]``), so
  the cap is a true upper bound and, for ``jitter <= 0.5``, the delay
  sequence for consecutive failures of one key is monotone non-decreasing
  until it reaches the cap.
- **Failure-count reset on success** is explicit (``forget``), called by
  the manager after a clean reconcile.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Hashable


class ExponentialBackoffLimiter:
    """controller-runtime-style per-key failure rate limiter."""

    def __init__(
        self,
        *,
        base_delay: float = 0.05,
        max_delay: float = 60.0,
        jitter: float = 0.2,
        seed: int = 0,
    ):
        if not 0.0 <= jitter <= 0.5:
            raise ValueError(
                f"jitter must be in [0, 0.5] to keep delays monotone, "
                f"got {jitter}"
            )
        if base_delay <= 0 or max_delay < base_delay:
            raise ValueError(
                f"need 0 < base_delay <= max_delay, got "
                f"{base_delay}/{max_delay}"
            )
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._failures: Dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def next_delay(self, key: Hashable) -> float:
        """Record one more failure for ``key`` and return the delay before
        its retry: ``min(base * 2^failures, max)``, jittered downward."""
        with self._lock:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
            r = self._rng.random()
        # 2^n overflows for pathological failure counts; clamp in log space.
        if n >= 64:
            raw = self.max_delay
        else:
            raw = min(self.base_delay * (2.0 ** n), self.max_delay)
        return raw * (1.0 - self.jitter * r)

    def failures(self, key: Hashable) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def forget(self, key: Hashable) -> None:
        """Reset the failure count after a successful reconcile."""
        with self._lock:
            self._failures.pop(key, None)

    def tracked_keys(self) -> int:
        """Number of keys currently holding a failure count (exported as a
        queue-health gauge: persistently failing objects accumulate here)."""
        with self._lock:
            return len(self._failures)
