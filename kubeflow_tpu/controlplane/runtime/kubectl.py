"""Real-cluster backend: the ApiServer surface spoken through ``kubectl``.

The in-memory store's interface is the seam where a real K8s client
substitutes (runtime/apiserver.py docstring); this module makes that claim
code. ``KubectlApiServer`` implements the same CRUD/list/watch surface by
shelling out to ``kubectl`` with JSON manifests (serde round-trip), so
every controller and ``tpuctl`` run unmodified against a live cluster —
the deployment mode the reference's controllers always assumed
(notebook_controller.go:81-250 runs in-cluster via controller-runtime).

Scope and honesty:
- CRs (TpuJob, Notebook, ..., our group's kinds) round-trip faithfully —
  their schema *is* our dataclasses.
- Core/Istio kinds cross the boundary through ``runtime/k8swire.py``,
  which produces REAL Kubernetes wire shapes (containerPort objects,
  requests/limits, RFC3339 timestamps, spec-nested Istio, ...); every
  outgoing manifest is validated against the vendored structural schemas
  in ``runtime/k8s_schema.py`` before kubectl ever sees it, and the
  kubectl test double applies the same validation to what arrives —
  the two-sided contract the reference gets from its vendored OpenAPI
  spec + envtest apiserver. Cluster-added fields beyond our dataclasses
  are dropped on read (controllers only read back what they wrote, plus
  status).
- Admission mutators are a server-side concern in a real cluster
  (admission-webhook); ``register_mutator`` here is a no-op with a log.
- Watch is poll-based (informer resync-style): a background poller (or
  explicit ``poll_now()`` in tests) lists watched kinds and diffs
  uid/resourceVersion into ADDED/MODIFIED/DELETED events.

Errors map onto the in-memory exceptions (NotFound/AlreadyExists/
Conflict), so controller retry behaviour is identical on both backends.
"""

from __future__ import annotations

import json
import queue
import subprocess
import threading
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.controlplane.api.serde import to_dict
from kubeflow_tpu.controlplane.api.types import (
    GROUP,
    KIND_REGISTRY,
    object_from_dict,
)
from kubeflow_tpu.controlplane.runtime.apiserver import (
    CLUSTER_SCOPED,
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from kubeflow_tpu.utils import get_logger

log = get_logger("kubectl")

# Kind -> kubectl resource argument. Our CRDs follow the <kind.lower()>s.GROUP
# convention; foreign kinds carry their own groups.
_CORE_RESOURCES = {
    "Pod": "pods",
    "Service": "services",
    "Namespace": "namespaces",
    "ServiceAccount": "serviceaccounts",
    "ResourceQuota": "resourcequotas",
    "Event": "events",
    "RoleBinding": "rolebindings.rbac.authorization.k8s.io",
    "VirtualService": "virtualservices.networking.istio.io",
    "AuthorizationPolicy": "authorizationpolicies.security.istio.io",
}


def resource_for(kind: str) -> str:
    if kind in _CORE_RESOURCES:
        return _CORE_RESOURCES[kind]
    if kind in KIND_REGISTRY:
        return f"{kind.lower()}s.{GROUP}"
    raise ApiError(f"unknown kind {kind!r}")


class KubectlApiServer:
    """ApiServer implementation backed by kubectl subprocess calls."""

    def __init__(
        self,
        kubectl: str = "kubectl",
        *,
        context: str = "",
        poll_interval: float = 1.0,
    ):
        self.kubectl = kubectl
        self.context = context
        self.poll_interval = poll_interval
        self._watchers: List[Tuple[Optional[str], "queue.Queue[WatchEvent]"]] = []
        # kind -> {(ns, name): (uid, resource_version, last_seen_object)}.
        # The object is kept so DELETED events can carry the full last-seen
        # state (controllers resolve owners from tombstones).
        self._snapshots: Dict[str, Dict[Tuple[str, str], Tuple[str, int, Any]]] = {}
        self._lock = threading.Lock()
        self._poller: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ----------------- plumbing -----------------

    def _run(self, args: List[str], stdin: Optional[str] = None) -> str:
        cmd = [self.kubectl]
        if self.context:
            cmd += ["--context", self.context]
        cmd += args
        proc = subprocess.run(
            cmd, input=stdin, capture_output=True, text=True
        )
        if proc.returncode != 0:
            err = (proc.stderr or proc.stdout).strip()
            low = err.lower()
            if "notfound" in low or "not found" in low:
                raise NotFoundError(err)
            if "alreadyexists" in low or "already exists" in low:
                raise AlreadyExistsError(err)
            if "conflict" in low or "modified" in low:
                raise ConflictError(err)
            raise ApiError(f"kubectl {' '.join(args[:3])}: {err}")
        return proc.stdout

    def _ns_args(self, kind: str, namespace: str) -> List[str]:
        if kind in CLUSTER_SCOPED:
            return []
        return ["-n", namespace] if namespace else []

    @staticmethod
    def _from_manifest(data: dict, kind: str = "") -> Any:
        from kubeflow_tpu.controlplane.runtime.k8swire import from_wire

        return from_wire(data, kind=kind)

    @classmethod
    def _parse(cls, raw: str) -> Any:
        return cls._from_manifest(json.loads(raw))

    def _manifest(self, obj: Any) -> str:
        from kubeflow_tpu.controlplane.runtime.k8s_schema import validate
        from kubeflow_tpu.controlplane.runtime.k8swire import to_wire

        data = to_wire(obj)
        errors = validate(data)
        if errors:
            # Fail HERE, not at the cluster: an invalid manifest reaching
            # a real apiserver is a controller bug, and the vendored
            # schema is the contract that catches it in-process.
            raise ApiError(
                f"manifest for {data.get('kind')}/"
                f"{data.get('metadata', {}).get('name')} fails k8s schema "
                f"validation: {'; '.join(errors[:5])}")
        return json.dumps(data)

    # ----------------- CRUD -----------------

    def pod_logs(self, name: str, namespace: str = "default") -> str:
        """Container logs via ``kubectl logs`` (tpuctl logs backend)."""
        return self._run(["logs", name, "-n", namespace or "default"])

    def create(self, obj: Any) -> Any:
        out = self._run(["create", "-f", "-", "-o", "json"],
                        stdin=self._manifest(obj))
        return self._parse(out)

    def get(self, kind: str, name: str, namespace: str = "", *,
            copy: bool = True) -> Any:
        # ``copy`` is accepted for interface parity with the in-memory
        # server's zero-copy read path; kubectl objects are always freshly
        # parsed, so the flag is a no-op here.
        del copy
        out = self._run(
            ["get", resource_for(kind), name,
             *self._ns_args(kind, namespace), "-o", "json"]
        )
        return self._parse(out)

    def try_get(self, kind: str, name: str, namespace: str = "", *,
                copy: bool = True) -> Optional[Any]:
        try:
            return self.get(kind, name, namespace, copy=copy)
        except NotFoundError:
            return None

    def update(self, obj: Any) -> Any:
        out = self._run(["replace", "-f", "-", "-o", "json"],
                        stdin=self._manifest(obj))
        return self._parse(out)

    # get -> graft -> replace has a read-modify-write window a concurrent
    # writer can land in; bounded retries keep the in-memory contract
    # (update_status never Conflicts against a live object).
    STATUS_CONFLICT_RETRIES = 5

    def update_status(self, obj: Any) -> Any:
        # Replace only the status subresource: read the live object, graft
        # our status on, keep the live spec (concurrent spec writes win —
        # the same contract as InMemoryApiServer.update_status, whose
        # status write ALWAYS succeeds against a live object). A real
        # apiserver 409s when a writer slips between our read and replace;
        # retrying with a fresh read is exactly what controller-runtime's
        # retry.RetryOnConflict does, and without it the adapter would
        # surface spurious Conflicts the in-memory backend never raises.
        last: Exception
        for attempt in range(self.STATUS_CONFLICT_RETRIES):
            live = self.get(obj.kind, obj.metadata.name,
                            obj.metadata.namespace)
            live.status = obj.status
            try:
                out = self._run(
                    ["replace", "--subresource", "status",
                     "-f", "-", "-o", "json"],
                    stdin=self._manifest(live),
                )
                return self._parse(out)
            except ConflictError as e:
                last = e
                log.info("status write conflicted; rereading",
                         kv={"kind": obj.kind, "name": obj.metadata.name,
                             "attempt": attempt + 1})
        raise last

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._run(
            ["delete", resource_for(kind), name,
             *self._ns_args(kind, namespace), "--wait=false"]
        )

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        *,
        copy: bool = True,
    ) -> List[Any]:
        del copy        # interface parity; kubectl objects are always fresh
        args = ["get", resource_for(kind)]
        if kind in CLUSTER_SCOPED or namespace is None:
            if kind not in CLUSTER_SCOPED:
                args.append("--all-namespaces")
        else:
            args += ["-n", namespace]
        if label_selector:
            args += ["-l", ",".join(f"{k}={v}"
                                    for k, v in sorted(label_selector.items()))]
        args += ["-o", "json"]
        data = json.loads(self._run(args))
        out = [self._from_manifest(item, kind)
               for item in data.get("items", [])]
        return sorted(
            out, key=lambda o: (o.metadata.namespace, o.metadata.name)
        )

    def register_mutator(self, fn) -> None:
        log.info("mutators are server-side on the kubectl backend; ignoring",
                 kv={"mutator": getattr(fn, "__name__", repr(fn))})

    # ----------------- watch (poll-based informer) -----------------

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        if kind is None:
            # Polling every kind in the registry per cycle would hammer the
            # apiserver; no framework controller needs the unscoped form.
            raise ApiError(
                "kubectl backend requires kind-scoped watches "
                "(watch(None) unsupported)"
            )
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        # Informer contract: replay current state as ADDED on subscribe
        # (InMemoryApiServer.watch does; controllers registered after the
        # kind's first poll would otherwise never see existing objects).
        try:
            existing = self.list(kind)
        except ApiError:
            existing = []
        with self._lock:
            for o in existing:
                q.put(WatchEvent("ADDED", o))
            snap = self._snapshots.setdefault(kind, {})
            for o in existing:
                snap.setdefault(
                    (o.metadata.namespace, o.metadata.name),
                    (o.metadata.uid, o.metadata.resource_version, o),
                )
            self._watchers.append((kind, q))
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            self._watchers = [(k, w) for (k, w) in self._watchers if w is not q]

    def poll_now(self) -> int:
        """One synchronous poll cycle: list every watched kind, diff against
        the last snapshot, emit events. Returns events emitted. Tests (and
        run_until_idle-style drivers) call this; start_polling() runs it on
        a background thread for real deployments."""
        emitted = 0
        with self._lock:
            kinds = sorted({k for k, _ in self._watchers if k is not None})
            watchers = list(self._watchers)
        for kind in kinds:
            try:
                objs = self.list(kind)
            except ApiError as e:
                log.error("poll failed", kv={"kind": kind, "err": str(e)})
                continue
            with self._lock:
                prev = self._snapshots.get(kind, {})
                cur: Dict[Tuple[str, str], Tuple[str, int, Any]] = {}
                events: List[WatchEvent] = []
                for o in objs:
                    k = (o.metadata.namespace, o.metadata.name)
                    cur[k] = (o.metadata.uid, o.metadata.resource_version, o)
                    if k not in prev:
                        events.append(WatchEvent("ADDED", o))
                    elif prev[k][:2] != cur[k][:2]:
                        events.append(WatchEvent("MODIFIED", o))
                for o_key in set(prev) - set(cur):
                    # Tombstone carries the full last-seen object, matching
                    # the in-memory backend (controllers resolve the owning
                    # primary from owner_references on DELETED events).
                    events.append(WatchEvent("DELETED", prev[o_key][2]))
                self._snapshots[kind] = cur
                for ev in events:
                    for wk, q in watchers:
                        if wk is None or wk == kind:
                            q.put(ev)
                            emitted += 1
        return emitted

    def start_polling(self) -> None:
        if self._poller is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                self.poll_now()
                self._stop.wait(self.poll_interval)

        self._poller = threading.Thread(target=loop, daemon=True)
        self._poller.start()

    def stop_polling(self) -> None:
        if self._poller is None:
            return
        self._stop.set()
        self._poller.join(timeout=5)
        self._poller = None
