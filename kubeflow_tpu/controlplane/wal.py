"""Append-only write-ahead log behind ``Platform.save`` (ISSUE 6).

The snapshot file (``state.yaml``) is written only when someone calls
``Platform.save`` — a shard process killed mid-sweep loses everything
since the last save. The WAL closes that window: every committed API
write appends one fsync'd JSON record (via the apiserver's
``set_journal`` hook, under the store lock, in commit order, *before*
the write's watch event becomes visible), so a crashed shard replays to
its exact pre-crash state:

    snapshot (state.yaml) ∘ WAL records with rv > snapshot counter

This is the replay-from-checkpoint discipline VirtualFlow
(arxiv 2009.09523) applies to training state, applied to the control
plane's: restart = load checkpoint + replay the delta, never an
O(store) reconstruction from scratch.

Record format, one JSON object per line::

    {"rv": 17, "op": "put", "obj": {...camelCase manifest...}}
    {"rv": 18, "op": "del", "key": ["Pod", "ns-00", "job-0000-w0"]}

Crash tolerance on the log itself: a kill mid-append leaves a truncated
final line; replay stops at the first undecodable record (everything
before it was fsync'd and is trustworthy, nothing after it can be).

Compaction: ``Platform.save`` writes the snapshot atomically
(temp + ``os.replace``) and then compacts the WAL down to records newer
than the snapshot's resource-version counter — normally none, so the log
resets to empty instead of growing without bound.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Iterator, List, Optional

from kubeflow_tpu.controlplane.api import object_from_dict, to_dict
from kubeflow_tpu.utils import get_logger

log = get_logger("wal")

WAL_FILE = "wal.jsonl"


class WriteAheadLog:
    """One append-only log file, fsync'd per record by default.

    ``attach(api)`` installs the journal hook on an
    :class:`~kubeflow_tpu.controlplane.runtime.apiserver.InMemoryApiServer`;
    from then on every committed write lands in the log before its watch
    event is visible. ``replay(api)`` applies records (newer than the
    api's current resource-version counter) back into a freshly loaded
    store.
    """

    def __init__(self, path: str, *, fsync: bool = True):
        self.path = path
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # kftpu: allow(KF102): the WAL IS the journal discipline — this
        # append-only fsync'd stream is what JsonlJournal models; routing
        # it through the shared class would invert the layering.
        self._f = open(path, "a", encoding="utf-8")
        #: Records appended by THIS process (not the on-disk total).
        self.appended = 0

    # ----------------- journal side -----------------

    def attach(self, api: Any) -> None:
        api.set_journal(self._journal)

    def _journal(self, op: str, payload: Any, rv: int) -> None:
        if op == "put":
            rec = {"rv": rv, "op": "put", "obj": to_dict(payload)}
        else:
            kind, ns, name = payload
            rec = {"rv": rv, "op": "del", "key": [kind, ns, name]}
        line = json.dumps(rec, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self.appended += 1

    # ----------------- replay side -----------------

    def _read_records(self) -> Iterator[dict]:
        if not os.path.exists(self.path):
            return
        with open(self.path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    # Truncated tail from a crash mid-append: every record
                    # before this line was fsync'd; nothing at or after it
                    # is trustworthy. Stop, don't raise — this is the
                    # EXPECTED shape of a crash.
                    log.warning("wal truncated record, stopping replay",
                                kv={"path": self.path, "line": lineno})
                    return

    def records(self) -> List[dict]:
        return list(self._read_records())

    def replay(self, api: Any, *, after_rv: Optional[int] = None) -> int:
        """Apply records with ``rv > after_rv`` (default: the api's current
        counter) into ``api`` via the verbatim snapshot-restore seam — no
        resourceVersion bumps, no watch events, no journal re-entry.
        Returns the number of records applied and advances the api's
        resource-version counter to the newest replayed rv."""
        floor = api._rv if after_rv is None else int(after_rv)
        applied = 0
        max_rv = floor
        for rec in self._read_records():
            rv = int(rec.get("rv", 0))
            if rv <= floor:
                continue
            if rec["op"] == "put":
                api.load_snapshot(object_from_dict(rec["obj"]))
            else:
                kind, ns, name = rec["key"]
                api.drop_snapshot(kind, name, ns)
            max_rv = max(max_rv, rv)
            applied += 1
        if max_rv > api._rv:
            api._rv = max_rv
        return applied

    # ----------------- compaction -----------------

    def compact(self, upto_rv: int) -> int:
        """Drop records with ``rv <= upto_rv`` (they are covered by the
        snapshot just saved); returns records kept. Atomic: the survivors
        are written to a temp file and ``os.replace``d in."""
        with self._lock:
            keep = [rec for rec in self._read_records()
                    if int(rec.get("rv", 0)) > int(upto_rv)]
            self._f.close()
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in keep:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            # kftpu: allow(KF102): reopening the WAL's own stream after
            # compaction — same in-discipline append as __init__.
            self._f = open(self.path, "a", encoding="utf-8")
        return len(keep)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except ValueError:
                pass


def wal_path(state_dir: str) -> str:
    return os.path.join(state_dir, WAL_FILE)
