"""Cross-shard admission ledger behind the leader lease (ISSUE 8, the
PR-6 follow-up).

The sharded control plane routes TpuJobs by namespace, so two shards
each see only their own jobs — a per-shard ``capacity`` map lets both
admit "the last v5e-16 slice" at once (double-admit). This module makes
slice-capacity reservations a SINGLETON service owned by whichever
shard holds the leader lease:

- :class:`CapacityLedger` — the authoritative ledger: capacity map plus
  ``uid -> (slice_type, num_slices)`` reservations. A gang holds its
  reservation from admission until the owning controller releases it
  (terminal phase / deletion / parked). Reserve is idempotent per uid.
- :class:`LedgerService` — a thread the LEASE-HOLDING shard runs: it
  answers requests arriving on its serve pipe against the authoritative
  ledger. Every mutation is journaled (fsync'd jsonl) when a journal
  path is given, so the NEXT leader replays to the exact reservation
  state after a failover — the same WAL discipline the store uses.
- :class:`LedgerClient` — the :class:`TpuJobController` hook
  (``ledger=``): ``try_reserve`` / ``release`` over the shard's pipe,
  request-id-matched (stale replies dropped), with a timeout verdict
  that fails CLOSED (the gang parks Pending and retries; an unreachable
  ledger must never admit).
- :class:`LedgerRelay` — the parent-process transport thread: forwards
  each shard's requests to the current leader's serve pipe. Pure
  routing, no ledger state — the authority stays behind the lease.

Why pipes + a relay instead of one shared ``mp.Queue``: a queue's
reader lock is held WHILE blocked in ``get``, so SIGKILLing the leader
mid-poll leaves the lock owned by a dead process and deadlocks every
future leader. Pipe ends are single-process; a killed peer can at worst
leave its own stream torn, which the relay absorbs as a timeout — and a
timeout is exactly the fail-closed path.
"""

from __future__ import annotations

import os
import threading
from multiprocessing.connection import wait as conn_wait
from typing import Dict, Optional, Tuple

from kubeflow_tpu.utils import get_logger, locktrace
from kubeflow_tpu.utils.journal import JsonlJournal

log = get_logger("ledger")

LEDGER_JOURNAL = "ledger.jsonl"

#: client_id the parent's own diagnostic client uses with the relay.
PARENT_CLIENT = -1


def ledger_journal_path(state_dir: str) -> str:
    return os.path.join(state_dir, LEDGER_JOURNAL)


class CapacityLedger:
    """Authoritative slice-capacity reservations. Thread-safe."""

    def __init__(self, capacity: Dict[str, int]):
        self._capacity = {k: int(v) for k, v in capacity.items()}
        self._held: Dict[str, Tuple[str, int]] = {}
        self._lock = threading.Lock()

    def reserve(self, uid: str, slice_type: str,
                num_slices: int) -> Tuple[Optional[str], bool]:
        """``(verdict, changed)``: verdict None = reserved (idempotent
        per uid — re-admitting the same gang re-checks against everyone
        else), else the blocking reason. ``changed`` is False when the
        call left the ledger exactly as it was (the steady-state
        re-reserve every reconcile performs) — the journal skips those,
        or it would fsync one redundant record per reconcile per job."""
        with self._lock:
            cap = self._capacity.get(slice_type, 0)
            in_use = sum(
                n for held_uid, (st, n) in self._held.items()
                if st == slice_type and held_uid != uid
            )
            if in_use + num_slices > cap:
                # A blocked gang must not keep an older reservation.
                dropped = self._held.pop(uid, None) is not None
                return (f"{in_use}/{cap} {slice_type} slices reserved "
                        "cluster-wide", dropped)
            want = (slice_type, int(num_slices))
            changed = self._held.get(uid) != want
            self._held[uid] = want
            return (None, changed)

    def try_reserve(self, uid: str, slice_type: str,
                    num_slices: int) -> Optional[str]:
        return self.reserve(uid, slice_type, num_slices)[0]

    def release(self, uid: str) -> bool:
        with self._lock:
            return self._held.pop(uid, None) is not None

    def held_uids(self) -> list:
        with self._lock:
            return sorted(self._held)

    def records(self) -> list:
        """The live reservations as journal records — what a compacted
        journal contains."""
        with self._lock:
            return [
                {"op": "reserve", "uid": uid, "slice_type": st,
                 "num_slices": n}
                for uid, (st, n) in sorted(self._held.items())
            ]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            in_use: Dict[str, int] = {}
            for st, n in self._held.values():
                in_use[st] = in_use.get(st, 0) + n
            return {
                "capacity": dict(self._capacity),
                "in_use": in_use,
                "reservations": len(self._held),
            }


class _Journal(JsonlJournal):
    """The shared fsync'd-jsonl discipline (utils/journal.py) plus the
    ledger-specific replay: re-apply reserve/release records into a
    :class:`CapacityLedger`. Before PR 16 this was a second hand-rolled
    appender — exactly the duplication KF102 now flags."""

    def replay_into(self, ledger: CapacityLedger) -> int:
        n = 0
        for rec in self.read(self.path):
            if rec.get("op") == "reserve":
                ledger.try_reserve(rec["uid"], rec["slice_type"],
                                   rec["num_slices"])
            elif rec.get("op") == "release":
                ledger.release(rec["uid"])
            n += 1
        return n


class LedgerService:
    """The leader-side half: answers ``(req_id, op, args)`` requests on
    ``serve_conn`` against the authoritative :class:`CapacityLedger`.
    ``start()`` replays the journal first — a new leader resumes the OLD
    leader's reservation state, which is what makes failover safe rather
    than a fresh double-admit window."""

    def __init__(self, capacity: Dict[str, int], serve_conn, *,
                 journal_path: str = "", fsync: bool = True,
                 tracer=None):
        self.ledger = CapacityLedger(capacity)
        self.serve_conn = serve_conn
        self.journal = _Journal(journal_path, fsync)
        # Cross-shard trace stitching (ISSUE 10): requests carry the
        # caller's (trace_id, span_id); with a tracer the service
        # records one `ledger.<op>` span PER request that adopts the
        # caller's trace id and links back to the calling span — the
        # gang's `tpuctl trace` timeline then includes its cross-shard
        # reserve round-trip instead of an orphan span on the
        # lease-holding shard.
        self.tracer = tracer
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.served = 0

    def start(self) -> "LedgerService":
        replayed = self.journal.replay_into(self.ledger)
        if replayed:
            log.info("ledger journal replayed", kv={
                "records": replayed,
                "reservations": self.ledger.snapshot()["reservations"],
            })
            # Compact behind the replay: the next failover replays only
            # the live reservations, never the whole reserve/release
            # history.
            self.journal.rewrite(self.ledger.records())
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="kftpu-ledger")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.journal.close()

    def handle(self, op: str, args: tuple, ctx=None):
        """One ledger operation (journal included) — the serve loop's
        body, also callable directly by a leader-local client. ``ctx``
        is the caller's span context: the operation is recorded as a
        span in the CALLER's trace (id adopted, link back)."""
        if self.tracer is not None and ctx:
            ctx = (str(ctx[0]), str(ctx[1]))
            with self.tracer.span(f"ledger.{op}", links=[ctx],
                                  trace_id=ctx[0]) as sp:
                payload = self._handle(op, args)
                if op == "reserve":
                    sp.attrs.update({
                        "uid": args[0], "slice_type": args[1],
                        "num_slices": args[2],
                        "verdict": payload or "reserved",
                    })
                elif op == "release":
                    sp.attrs["uid"] = args[0]
                return payload
        return self._handle(op, args)

    def _handle(self, op: str, args: tuple):
        if op == "reserve":
            uid, slice_type, num_slices = args
            verdict, changed = self.ledger.reserve(uid, slice_type,
                                                   num_slices)
            # Journal only MUTATIONS: the steady-state idempotent
            # re-reserve (every reconcile of every running gang) must
            # not fsync a record. A denial that dropped a stale hold is
            # a mutation too — journal the release so replay converges.
            if changed:
                if verdict is None:
                    self.journal.append({"op": "reserve", "uid": uid,
                                         "slice_type": slice_type,
                                         "num_slices": num_slices})
                else:
                    self.journal.append({"op": "release", "uid": uid})
            return verdict
        if op == "release":
            (uid,) = args
            if self.ledger.release(uid):
                self.journal.append({"op": "release", "uid": uid})
            return None
        if op == "prune":
            # Anti-entropy GC (operator/parent-driven): drop every
            # reservation whose gang is no longer alive anywhere — the
            # leak path is a gang deleted while its owning controller
            # was down (nobody left to release by uid).
            (live_uids,) = args
            live = set(live_uids)
            dropped = [uid for uid in self.ledger.held_uids()
                       if uid not in live]
            for uid in dropped:
                if self.ledger.release(uid):
                    self.journal.append({"op": "release", "uid": uid})
            if dropped:
                log.warning("ledger pruned orphan reservations",
                            kv={"dropped": len(dropped)})
            return dropped
        if op == "snapshot":
            return self.ledger.snapshot()
        return f"unknown ledger op {op!r}"

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                if not self.serve_conn.poll(0.05):
                    continue
                msg = self.serve_conn.recv()
                # 4-tuples carry the caller's span context; 3-tuples
                # (pre-stitching peers) still serve.
                req_id, op, args = msg[0], msg[1], msg[2]
                ctx = msg[3] if len(msg) > 3 else None
                payload = self.handle(op, args, ctx)
                self.served += 1
                self.serve_conn.send((req_id, payload))
            except (EOFError, OSError):
                return          # transport gone: leadership moved on
            except Exception as e:  # noqa: BLE001 — service must survive
                log.error("ledger request failed", kv={"err": repr(e)})


class LedgerClient:
    """The shard-side handle the TpuJobController admission path calls.
    Fails CLOSED: a timeout (leader dead, election in flight) reports
    the gang blocked — it parks Pending and retries on its admission
    requeue, which is exactly the window a failover needs."""

    UNAVAILABLE = ("admission ledger unavailable (leader failover in "
                   "progress); retrying")

    def __init__(self, conn, *, timeout_s: float = 5.0):
        self.conn = conn
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._seq = 0

    def _call(self, op: str, args: tuple):
        import time as _time

        from kubeflow_tpu.utils.tracing import current_span

        # Carry the calling span's context over the pipe (the reconcile
        # span of the admitting controller): the leader-side service
        # records the operation INTO that trace, so `tpuctl trace`
        # stitches the cross-shard round-trip into one timeline.
        span = current_span()
        ctx = (span.trace_id, span.span_id) if span is not None else None
        with self._lock:
            self._seq += 1
            req_id = self._seq
            try:
                self.conn.send((req_id, op, args, ctx))
            except (OSError, ValueError):
                raise TimeoutError
            t0 = _time.monotonic()
            while True:
                remaining = self.timeout_s - (_time.monotonic() - t0)
                if remaining <= 0 or not self.conn.poll(remaining):
                    raise TimeoutError
                try:
                    got_id, payload = self.conn.recv()
                except (EOFError, OSError):
                    raise TimeoutError
                if got_id == req_id:
                    return payload
                # Stale reply from a timed-out earlier call: drop it —
                # matching on req_id keeps a late answer from being read
                # as the verdict of a NEWER question.

    def try_reserve(self, uid: str, slice_type: str,
                    num_slices: int) -> Optional[str]:
        try:
            return self._call("reserve", (uid, slice_type, num_slices))
        except TimeoutError:
            return self.UNAVAILABLE

    def release(self, uid: str) -> None:
        try:
            self._call("release", (uid,))
        except TimeoutError:
            pass    # the journal replay / later reconcile releases it

    def snapshot(self) -> Optional[Dict[str, object]]:
        try:
            return self._call("snapshot", ())
        except TimeoutError:
            return None


class LocalLedgerClient:
    """In-process client for a single-process deployment (or tests):
    same interface, no transport."""

    def __init__(self, service: LedgerService):
        self.service = service

    @staticmethod
    def _ctx():
        from kubeflow_tpu.utils.tracing import current_span

        span = current_span()
        return (span.trace_id, span.span_id) if span is not None else None

    def try_reserve(self, uid, slice_type, num_slices):
        return self.service.handle("reserve", (uid, slice_type,
                                               num_slices), self._ctx())

    def release(self, uid) -> None:
        self.service.handle("release", (uid,), self._ctx())

    def snapshot(self):
        return self.service.handle("snapshot", ())


class LedgerRelay:
    """Parent-side transport: forwards each client pipe's requests to
    the CURRENT leader's serve pipe and routes the answer back. Holds NO
    ledger state — a relay restart loses nothing, and a dead leader
    surfaces as a timeout (the client's fail-closed path). ``leader_of``
    is read per request, so an election immediately redirects traffic."""

    def __init__(self, client_conns: Dict[int, object],
                 serve_conns: Dict[int, object], leader_of,
                 *, leader_timeout_s: float = 5.0):
        self.client_conns = dict(client_conns)
        self.serve_conns = dict(serve_conns)
        self.leader_of = leader_of          # () -> Optional[int]
        self.leader_timeout_s = leader_timeout_s
        # locktrace factory: the relay's connection lock is the shard
        # transport's hot lock — traced under the sharded chaos soak.
        self._conn_lock = locktrace.lock("ledger.relay")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.forwarded = 0
        # Relay-global forward ids: per-CLIENT req_ids collide across
        # clients (every LedgerClient counts from 1), so a late reply to
        # shard A's timed-out request could otherwise be matched to
        # shard B's next forward carrying the same number.
        self._fwd_seq = 0

    def replace(self, client_id: int, client_conn, serve_conn) -> None:
        """Swap in FRESH pipes for a (re)spawned shard, closing the old
        ones. A shard SIGKILLed mid-send leaves a torn pickle frame in
        its old pipe that no amount of recv() resynchronizes — the
        respawn must start on clean streams."""
        with self._conn_lock:
            old_client = self.client_conns.get(client_id)
            old_serve = self.serve_conns.get(client_id)
            self.client_conns[client_id] = client_conn
            self.serve_conns[client_id] = serve_conn
        for old in (old_client, old_serve):
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass

    def start(self) -> "LedgerRelay":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kftpu-ledger-relay")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _forward(self, client_id: int, msg) -> None:
        import time as _time

        # 4th element (when present) is the caller's span context — pure
        # passthrough: the relay neither opens spans nor rewrites it.
        req_id, op, args = msg[0], msg[1], msg[2]
        ctx = msg[3] if len(msg) > 3 else None
        leader = self.leader_of()
        reply = (req_id,
                 LedgerClient.UNAVAILABLE if op == "reserve" else None)
        if leader is not None:
            with self._conn_lock:
                conn = self.serve_conns.get(leader)
            if conn is not None:
                # Re-tag with a relay-global id and match the answer to
                # THIS forward: a delayed reply to an earlier timed-out
                # forward (possibly from a DIFFERENT client whose own
                # req_id happens to collide) must be dropped, never
                # delivered as this request's verdict — mis-delivering a
                # 'reserved' is exactly the double-admit this service
                # exists to prevent.
                self._fwd_seq += 1
                fwd_id = self._fwd_seq
                try:
                    conn.send((fwd_id, op, args, ctx))
                    deadline = _time.monotonic() + self.leader_timeout_s
                    while True:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0 or not conn.poll(remaining):
                            break
                        got_id, payload = conn.recv()
                        if got_id == fwd_id:
                            reply = (req_id, payload)
                            break
                except (EOFError, OSError):
                    pass        # leader died mid-request: fail closed
        try:
            self.client_conns[client_id].send(reply)
            self.forwarded += 1
        except (EOFError, OSError):
            pass                # requester died: nothing to answer

    def _run(self) -> None:
        while not self._stop.is_set():
            # Snapshot per pass: `replace` swaps in fresh pipes when a
            # shard respawns (old ends are closed — wait() then drops
            # them here rather than erroring forever).
            with self._conn_lock:
                conns = {id(c): (cid, c)
                         for cid, c in self.client_conns.items()}
            if not conns:
                self._stop.wait(0.05)
                continue
            try:
                ready = conn_wait([c for _, c in conns.values()],
                                  timeout=0.05)
            except OSError:
                continue        # a conn closed mid-wait: re-snapshot
            for conn in ready:
                cid, _ = conns[id(conn)]
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    # Dead shard: its end hit EOF, which wait() reports
                    # as forever-readable — retire the conn or this loop
                    # busy-spins until the respawn swaps in fresh pipes.
                    with self._conn_lock:
                        if self.client_conns.get(cid) is conn:
                            del self.client_conns[cid]
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
                except Exception:   # torn pickle from a mid-send kill
                    continue
                self._forward(cid, msg)
