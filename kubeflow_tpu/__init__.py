"""kubeflow_tpu — a TPU-native ML control plane and compute framework.

A ground-up rebuild of the capabilities of kubeflow/kubeflow (reference at
/root/reference) designed for Cloud TPU rather than GPU node pools:

- ``topology``:   TPU slice types (v4/v5e/v5p/v6e) and ICI-topology-aware
  mesh planning — the first-class concept that replaces the reference's
  ``nvidia.com/gpu`` resource strings
  (reference: components/jupyter-web-app/backend/kubeflow_jupyter/common/utils.py:390-443).
- ``parallel``:   mesh axes (dp/fsdp/tp/sp/ep), sharding rules, ring-attention
  and Ulysses sequence parallelism, expert-parallel all-to-all.
- ``ops``:        TPU kernels (pallas) and reference implementations.
- ``models``:     flagship model zoo (Llama, Mixtral, ResNet-50, ViT) —
  replaces the reference's tf_cnn_benchmarks payload images
  (reference: tf-controller-examples/tf-cnn/).
- ``train``:      sharded training loop, orbax checkpoint service, auto-resume.
- ``serving``:    continuous-batching TPU inference engine.
- ``controlplane``: CRD types + controllers (TpuJob, Notebook, Profile,
  PodDefault, Tensorboard), in-memory API server for envtest-style testing,
  kfam-equivalent access management
  (reference: components/{notebook,profile,tensorboard}-controller/,
  components/admission-webhook/, components/access-management/).
- ``tools``:      ``tpuctl`` deployment CLI (kfctl equivalent, reference:
  bootstrap/).
"""

from kubeflow_tpu.version import __version__

__all__ = ["__version__"]
