"""The ONE fsync'd-jsonl journal discipline (KF102).

Every ``*.jsonl`` append in the package routes through this class (or a
subclass): torn-tail-tolerant replay, single-generation rollover (the
``Tracer.rotate_jsonl`` discipline from PR 10), and atomic temp+rename
compaction. The goodput ledger, the SLO engine's ``alerts.jsonl`` and
the capacity ledger's journal all share it — PR 16's KF102 rule flags
any open-for-append on a ``.jsonl`` path outside ``obs/``/``utils/``
precisely so a fourth hand-rolled copy (the pre-PR-16 state: goodput
and ledger each carried their own) cannot reappear.
"""

from __future__ import annotations

import json
import os
from typing import List


class JsonlJournal:
    """fsync'd jsonl appender with torn-tail-tolerant replay and
    single-generation rollover: past ``rotate_bytes`` the file moves to
    ``<path>.1`` and appends restart fresh — owners write a compacting
    state record as the new head so the current generation is always
    self-contained."""

    def __init__(self, path: str, fsync: bool):
        self.path = path
        self.fsync = fsync
        self._f = None

    def append(self, rec: dict) -> None:
        if not self.path:
            return
        if self._f is None:
            self._f = open(self.path, "a")
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def maybe_rotate(self, max_bytes: int) -> bool:
        """Roll the journal to ``<path>.1`` once it outgrows
        ``max_bytes`` (atomic rename replacing any prior generation).
        Callers check BEFORE appending a new record and, on True, write
        their state-compaction record as the fresh generation's head —
        every record journaled so far has already been applied, so that
        head covers the rotated-out generation exactly and the current
        file is self-contained even after ``.1`` is itself replaced."""
        if not self.path or self._f is None or max_bytes <= 0:
            return False
        if self._f.tell() <= max_bytes:
            return False
        self._f.close()
        self._f = None
        os.replace(self.path, self.path + ".1")
        return True

    @staticmethod
    def generations(path: str) -> List[str]:
        """On-disk generations, oldest first (``<path>.1`` then
        ``<path>``), existing files only — replay reads ALL of them."""
        if not path:
            return []
        return [p for p in (path + ".1", path) if os.path.exists(p)]

    @staticmethod
    def read(path: str) -> List[dict]:
        out: List[dict] = []
        if not path or not os.path.exists(path):
            return out
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    break       # torn tail record: crash mid-append
        return out

    @classmethod
    def read_generations(cls, path: str) -> List[dict]:
        out: List[dict] = []
        for p in cls.generations(path):
            out.extend(cls.read(p))
        return out

    @staticmethod
    def compact(path: str, head_rec: dict) -> None:
        """Replace the journal (and any ``.1`` generation it covers)
        with one state record: temp write, fsync, atomic rename."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(head_rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if os.path.exists(path + ".1"):
            os.remove(path + ".1")

    def rewrite(self, records: list) -> None:
        """Compact to exactly ``records`` (atomic temp+rename, same
        discipline as Platform.save) — the replay-everything cost of a
        failover stays bounded by live state, not by history."""
        if not self.path:
            return
        self.close()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
