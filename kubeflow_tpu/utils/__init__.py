from kubeflow_tpu.utils.logging import get_logger
from kubeflow_tpu.utils.monitoring import (
    Counter,
    Gauge,
    Heartbeat,
    MetricsRegistry,
    global_registry,
)

__all__ = [
    "get_logger",
    "Counter",
    "Gauge",
    "Heartbeat",
    "MetricsRegistry",
    "global_registry",
]
