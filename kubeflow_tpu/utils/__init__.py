from kubeflow_tpu.utils.logging import configure as configure_logging
from kubeflow_tpu.utils.logging import get_logger
from kubeflow_tpu.utils.monitoring import (
    Counter,
    Gauge,
    Heartbeat,
    Histogram,
    MetricsRegistry,
    global_registry,
)
from kubeflow_tpu.utils.tracing import Span, Tracer, global_tracer

__all__ = [
    "configure_logging",
    "get_logger",
    "Counter",
    "Gauge",
    "Heartbeat",
    "Histogram",
    "MetricsRegistry",
    "global_registry",
    "Span",
    "Tracer",
    "global_tracer",
]
