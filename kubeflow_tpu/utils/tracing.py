"""Dependency-free in-process tracing for the control plane.

The reference platform stops at counters-plus-heartbeat per controller
(profile-controller/controllers/monitoring.go); at fleet scale the question
those can't answer is *where time goes* between a write, its watch
delivery, its queue wait, and the reconcile that retires it — the
latency-decomposition problem of arxiv 2011.03641 / 1908.08082. This
module is the span layer the apiserver and reconciler kernel thread their
hot paths through:

- :class:`Span` — name, attrs, ids, monotonic start/duration, parent id,
  and causal *links* (the write-RV → reconcile edge: a reconcile span
  links to the write span whose watch event enqueued its key, so one
  trace covers "tpuctl write → watch event → reconcile → status update").
- :class:`Tracer` — contextvar-based propagation (``tracer.span(...)``
  nests: spans started inside become children, sharing the trace id), a
  bounded ring-buffer exporter, and JSONL export/import so ``tpuctl
  trace`` can reconstruct timelines across processes.

Threads started *after* a span begins do not inherit the contextvar
(Python threads snapshot a fresh context) — cross-thread causality is
carried explicitly instead: watch events stamp the writing span's context
(``SpanContext``), and the ControllerManager passes it through its queue
as a link (tested in tests/test_tracing.py). Everything here is pure
stdlib and allocation-light: no clocks beyond ``time``, no globals beyond
one default tracer.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

#: (trace_id, span_id) — the wire-size identity of a span, carried on
#: watch events and queue entries instead of the span object itself.
SpanContext = Tuple[str, str]

_ids = itertools.count(1)
# Per-process id prefix: pid low bits + 4 random bytes drawn ONCE at
# import (os.urandom — not the `random` module, whose seeded streams
# chaos tests depend on). pid bits alone collide under pid recycling,
# and tpuctl appends every invocation's spans to one trace.jsonl —
# colliding ids would merge unrelated sessions into one causal timeline.
_pid_stamp = f"{os.getpid() & 0xffff:04x}{os.urandom(4).hex()}"

#: One process-wide "current span" context, shared by every Tracer (see
#: Tracer.__init__). Read via :func:`current_span`.
_CURRENT_SPAN: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("kftpu_current_span", default=None)


def current_span() -> Optional["Span"]:
    """The span currently open on this thread/context, whichever tracer
    opened it — the hook structured logging uses to stamp trace ids."""
    return _CURRENT_SPAN.get()


def _new_id() -> str:
    # Monotonic per-process counter + pid stamp: unique enough for trace
    # reconstruction across tpuctl invocations, deterministic within one
    # process (no RNG draw — chaos seeds must not shift under tracing).
    return f"{_pid_stamp}{next(_ids):010x}"


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start_unix: float = 0.0         # wall clock, for cross-process ordering
    start_mono: float = 0.0         # monotonic, for duration math
    duration_s: float = -1.0        # -1 while open
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    links: List[SpanContext] = dataclasses.field(default_factory=list)

    @property
    def context(self) -> SpanContext:
        return (self.trace_id, self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix": self.start_unix,
            "duration_s": self.duration_s,
            "attrs": self.attrs,
            "links": [list(l) for l in self.links],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Span":
        return cls(
            name=d["name"],
            trace_id=d["trace_id"],
            span_id=d["span_id"],
            parent_id=d.get("parent_id", ""),
            start_unix=float(d.get("start_unix", 0.0)),
            start_mono=0.0,
            duration_s=float(d.get("duration_s", -1.0)),
            attrs=dict(d.get("attrs", {})),
            links=[tuple(l) for l in d.get("links", [])],
        )


class Tracer:
    """Bounded in-process span recorder with contextvar propagation.

    ``capacity`` bounds the finished-span ring buffer (oldest evicted
    first); a long-running platform can trace forever without growing.
    """

    def __init__(self, capacity: int = 8192):
        self._spans: "deque[Span]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._total = 0             # spans ever recorded (incl. evicted)
        self._exported_upto = 0     # high-water mark of export_new_jsonl
        # The ACTIVE span is process-wide (one shared contextvar), not
        # per-tracer: tracers differ only in where finished spans are
        # ring-buffered. Log↔trace correlation (utils/logging.py) must see
        # the current span no matter which tracer instance opened it —
        # Platform and the benches all run private tracers.
        self._current = _CURRENT_SPAN

    # ------------- span lifecycle -------------

    def current(self) -> Optional[Span]:
        return self._current.get()

    def current_context(self) -> Optional[SpanContext]:
        s = self._current.get()
        return s.context if s is not None else None

    def start(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        links: Sequence[SpanContext] = (),
        trace_id: Optional[str] = None,
    ) -> Span:
        """Open a span and make it current; pair with :meth:`finish`.
        Parentage: an explicit ``trace_id`` wins (the adopt-the-linked-
        write's-trace case), else the contextvar's current span (nesting),
        else a fresh trace. The imperative half of :meth:`span` — the
        apiserver hot path uses it directly to skip generator-context-
        manager overhead (profiled at ~3% of a control-plane sweep)."""
        parent = self._current.get()
        if trace_id is None:
            trace_id = parent.trace_id if parent is not None else _new_id()
        s = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_id(),
            parent_id=parent.span_id if parent is not None else "",
            start_unix=time.time(),
            start_mono=time.monotonic(),
            attrs=attrs if attrs is not None else {},
            links=list(links),
        )
        s._token = self._current.set(s)     # type: ignore[attr-defined]
        return s

    def finish(self, s: Span) -> None:
        """Close a :meth:`start`-opened span: stamp duration, restore the
        previous current span, record into the ring."""
        s.duration_s = time.monotonic() - s.start_mono
        token = getattr(s, "_token", None)
        if token is not None:
            self._current.reset(token)
        with self._lock:
            self._spans.append(s)
            self._total += 1

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        links: Sequence[SpanContext] = (),
        trace_id: Optional[str] = None,
    ) -> Iterator[Span]:
        """Context-managed :meth:`start`/:meth:`finish`."""
        s = self.start(name, attrs, links, trace_id)
        try:
            yield s
        finally:
            self.finish(s)

    # ------------- read / export -------------

    def spans(self, name: Optional[str] = None,
              **attr_filters: Any) -> List[Span]:
        """Finished spans, oldest first, optionally filtered by span name
        and exact attr values."""
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        for k, v in attr_filters.items():
            out = [s for s in out if s.attrs.get(k) == v]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def export_jsonl(self, path: str, append: bool = True) -> int:
        """Write the ring buffer as JSON lines (one span per line); returns
        spans written. Append mode is how successive ``tpuctl`` processes
        accumulate one causal record under the state dir."""
        with self._lock:
            out = list(self._spans)
        mode = "a" if append else "w"
        with open(path, mode) as f:
            for s in out:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(out)

    def export_new_jsonl(self, path: str) -> int:
        """Append only spans recorded since the last ``export_new_jsonl``
        call — repeated exports (Platform.save per tpuctl subcommand) never
        duplicate lines. Spans evicted from the ring before being exported
        are gone (bounded-memory contract)."""
        with self._lock:
            fresh = self._total - self._exported_upto
            out = list(self._spans)[-fresh:] if fresh > 0 else []
            self._exported_upto = self._total
        if not out:
            return 0
        with open(path, "a") as f:
            for s in out:
                f.write(json.dumps(s.to_dict()) + "\n")
        return len(out)

    @staticmethod
    def rotate_jsonl(path: str, max_bytes: int = 4 << 20) -> bool:
        """Single-generation rollover for an append-accumulated span
        file: past ``max_bytes`` the file moves to ``<path>.1`` (atomic
        rename, replacing any previous generation) and appends restart
        on a fresh file. Unlike :meth:`trim_jsonl` this never rewrites
        or drops the newest spans mid-file — readers (`tpuctl trace`)
        load both generations, so a rollover between two commands can't
        amputate the causal record they straddle."""
        try:
            if os.path.getsize(path) <= max_bytes:
                return False
        except OSError:
            return False
        os.replace(path, path + ".1")
        return True

    @staticmethod
    def generations(path: str) -> List[str]:
        """The on-disk generations of a rotated span file, oldest first
        (``<path>.1`` then ``<path>``), existing files only."""
        return [p for p in (path + ".1", path) if os.path.exists(p)]

    @staticmethod
    def trim_jsonl(path: str, max_bytes: int = 4 << 20) -> None:
        """Bound an append-accumulated span file: when it outgrows
        ``max_bytes``, keep the newest half (whole lines). The in-memory
        ring is bounded; the state-dir file must be too, or a scripted
        tpuctl loop grows it — and every ``tpuctl trace`` load — forever."""
        try:
            if os.path.getsize(path) <= max_bytes:
                return
        except OSError:
            return
        with open(path) as f:
            lines = f.readlines()
        keep, size = [], 0
        for line in reversed(lines):
            size += len(line)
            if size > max_bytes // 2:
                break
            keep.append(line)
        with open(path, "w") as f:
            f.writelines(reversed(keep))

    @staticmethod
    def load_jsonl(path: str) -> List[Span]:
        spans: List[Span] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    spans.append(Span.from_dict(json.loads(line)))
        return spans


def assemble_trace(
    spans: Sequence[Span], kind: str, name: str, namespace: str = ""
) -> List[Span]:
    """The causal slice of ``spans`` for one object: seed with every span
    whose attrs reference (kind, name[, namespace]) — apiserver verb spans
    carry kind/name/namespace, reconcile spans carry name/namespace — then
    close over shared trace ids (write → watch → reconcile → status-update
    chains share the originating write's trace id via span links). Sorted
    by wall-clock start."""
    def references(s: Span) -> bool:
        # Seeds are apiserver verb spans carrying an EXACT kind match;
        # reconcile spans (no kind attr) join via the trace-id closure
        # only — otherwise tracing a nonexistent kind/name would adopt
        # another kind's trace wholesale.
        a = s.attrs
        if a.get("name") != name or a.get("kind") != kind:
            return False
        ns = a.get("namespace")
        return not namespace or ns in (namespace, None, "")

    trace_ids = {s.trace_id for s in spans if references(s)}
    out = [s for s in spans if s.trace_id in trace_ids]
    return sorted(out, key=lambda s: (s.start_unix, s.span_id))


#: Default tracer: what the apiserver / reconciler kernel record into when
#: the caller doesn't wire a private one (Platform builds its own so state
#: dirs don't cross-contaminate).
global_tracer = Tracer()
