"""Runtime lock-order tracing + the workqueue per-key oracle (ISSUE 16).

The static rules in ``kubeflow_tpu/analysis`` catch invariants visible
in source; this module catches the ones only visible under load on the
multi-threaded control plane (PR 5's worker pool, PR 6's shard relay):

- :func:`lock` / :func:`rlock` — drop-in factories the named hot locks
  are built through (``ControllerManager``'s queue lock, the apiserver
  store lock, the serving LB state lock, the ledger relay's connection
  lock). Disabled (the default) they return plain ``threading``
  primitives — zero overhead. Enabled (:func:`enable` or
  ``KFTPU_LOCKTRACE=1``) they return traced wrappers that record, per
  acquisition: the owning thread, the acquisition stack, and — for every
  lock the thread already held — a lock-order edge ``held -> acquired``.
- :class:`LockTraceRegistry` — the edge graph. :meth:`cycles` reports
  any cycle in it (two threads taking the same pair of locks in opposite
  orders is a deadlock waiting for the right interleaving — the classic
  lock-order-inversion detector, cf. TSan's deadlock detector);
  :meth:`long_holds` reports acquisitions held past a threshold with the
  stack that took them (the hot-spot surface).
- :class:`WorkqueueOracle` — the per-key never-concurrent invariant
  (client-go workqueue semantics, PR 5): ``enter(ctl, key)`` /
  ``exit(ctl, key)`` around every reconcile; a second concurrent enter
  for the same (controller, key) is recorded as a violation with both
  stacks. The chaos soaks install one and assert it stays empty at
  ``workers=4``.

The chaos soaks (``chaos/soak.py``) enable tracing, run, and fold
:func:`report` into their reports; CI's chaos-smoke/shard-smoke stages
gate on zero cycles, zero leaked threads and a clean oracle.

Timing here is ``time.monotonic()`` on purpose: hold durations are
host-side diagnostics, not tick-domain state — this module is in
``utils/`` precisely so KF101's tick-domain rule never sees it.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

#: Stack depth kept per acquisition — enough to name the caller chain
#: without making every acquire O(full stack render).
_STACK_LIMIT = 12

_enabled = bool(int(os.environ.get("KFTPU_LOCKTRACE", "0") or "0"))


def _stack(skip: int = 2) -> List[str]:
    return [
        f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}:{f.name}"
        for f in traceback.extract_stack(limit=_STACK_LIMIT + skip)[:-skip]
    ]


class LockTraceRegistry:
    """Process-wide acquisition bookkeeping for traced locks.

    Its own mutex guards only this bookkeeping and is never held while
    blocking on a traced lock, so the tracer cannot introduce the
    ordering problems it exists to find."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # thread ident -> [(lock name, t_acquired)] in acquisition order.
        self._held: Dict[int, List[Tuple[str, float]]] = {}
        # (held name, acquired name) -> (count, sample stack).
        self._edges: Dict[Tuple[str, str], Tuple[int, List[str]]] = {}
        self._acquisitions: Dict[str, int] = {}
        # (name, held_s, release stack) past the threshold.
        self._long_holds: List[Tuple[str, float, List[str]]] = []
        self.long_hold_threshold_s = 0.5

    # ---------------- wrapper callbacks ----------------

    def note_acquired(self, name: str) -> None:
        ident = threading.get_ident()
        now = time.monotonic()
        stack: Optional[List[str]] = None
        with self._mu:
            self._acquisitions[name] = self._acquisitions.get(name, 0) + 1
            held = self._held.setdefault(ident, [])
            for prior, _t in held:
                if prior == name:
                    continue    # same-named pair: not an ordering edge
                key = (prior, name)
                if key not in self._edges:
                    if stack is None:
                        stack = _stack(skip=3)
                    self._edges[key] = (1, stack)
                else:
                    n, s = self._edges[key]
                    self._edges[key] = (n + 1, s)
            held.append((name, now))

    def note_released(self, name: str) -> None:
        ident = threading.get_ident()
        now = time.monotonic()
        with self._mu:
            held = self._held.get(ident, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] == name:
                    _n, t0 = held.pop(i)
                    if now - t0 >= self.long_hold_threshold_s:
                        self._long_holds.append(
                            (name, now - t0, _stack(skip=3)))
                    break
            if not held:
                self._held.pop(ident, None)

    # ---------------- reporting ----------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return {k: n for k, (n, _s) in self._edges.items()}

    def cycles(self) -> List[List[str]]:
        """Cycles in the lock-order graph, each as the lock-name path
        (``[a, b, a]`` = some thread took a then b while another took b
        then a). Deterministic: nodes visited in sorted order."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            for (a, b) in self._edges:
                adj.setdefault(a, []).append(b)
        for dsts in adj.values():
            dsts.sort()
        found: List[List[str]] = []
        seen_cycles = set()
        done = set()

        def dfs(node: str, path: List[str], on_path: set) -> None:
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    # Canonicalize on the smallest rotation so the same
                    # cycle found from two entry points dedups.
                    ring = cyc[:-1]
                    k = min(tuple(ring[i:] + ring[:i])
                            for i in range(len(ring)))
                    if k not in seen_cycles:
                        seen_cycles.add(k)
                        found.append(cyc)
                elif nxt not in done:
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.remove(nxt)
            done.add(node)

        for start in sorted(adj):
            if start not in done:
                dfs(start, [start], {start})
        return found

    def long_holds(self) -> List[Tuple[str, float, List[str]]]:
        with self._mu:
            return list(self._long_holds)

    def acquisitions(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._acquisitions)

    def edge_stacks(self) -> Dict[Tuple[str, str], List[str]]:
        with self._mu:
            return {k: list(s) for k, (_n, s) in self._edges.items()}

    def reset(self) -> None:
        with self._mu:
            self._held.clear()
            self._edges.clear()
            self._acquisitions.clear()
            self._long_holds.clear()


_registry = LockTraceRegistry()


class TracedLock:
    """``threading.Lock`` wrapper feeding the trace registry."""

    def __init__(self, name: str,
                 registry: Optional[LockTraceRegistry] = None):
        self.name = name
        self._registry = registry or _registry
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._registry.note_acquired(self.name)
        return got

    def release(self) -> None:
        self._registry.note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class TracedRLock:
    """``threading.RLock`` wrapper. Only the outermost acquire/release
    of a reentrant hold is traced: inner re-entries cannot change the
    ordering relation and would self-edge the graph."""

    def __init__(self, name: str,
                 registry: Optional[LockTraceRegistry] = None):
        self.name = name
        self._registry = registry or _registry
        self._inner = threading.RLock()
        self._depth: Dict[int, int] = {}
        # _depth is only ever touched while holding _inner, so it needs
        # no lock of its own.

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            ident = threading.get_ident()
            d = self._depth.get(ident, 0)
            self._depth[ident] = d + 1
            if d == 0:
                self._registry.note_acquired(self.name)
        return got

    def release(self) -> None:
        ident = threading.get_ident()
        d = self._depth.get(ident, 0) - 1
        if d <= 0:
            self._depth.pop(ident, None)
            self._registry.note_released(self.name)
        else:
            self._depth[ident] = d
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


# ---------------- module-level switch + factories ----------------


def enabled() -> bool:
    return _enabled


def enable(reset: bool = True) -> None:
    """Turn tracing ON for locks created AFTER this call (the factories
    consult the flag at construction, keeping the disabled path free)."""
    global _enabled
    _enabled = True
    if reset:
        _registry.reset()


def disable() -> None:
    global _enabled
    _enabled = False


def registry() -> LockTraceRegistry:
    return _registry


def lock(name: str):
    """A mutex for the named role: plain ``threading.Lock`` while
    tracing is off, a :class:`TracedLock` while it is on."""
    return TracedLock(name) if _enabled else threading.Lock()


def rlock(name: str):
    return TracedRLock(name) if _enabled else threading.RLock()


def report() -> Dict[str, object]:
    """The soak-end summary the chaos reports embed: cycles (must be
    empty), long holds, and per-lock acquisition counts."""
    return {
        "enabled": _enabled,
        "cycles": _registry.cycles(),
        "long_holds": [
            {"lock": n, "held_s": round(s, 3), "stack": st}
            for n, s, st in _registry.long_holds()
        ],
        "acquisitions": _registry.acquisitions(),
        "edges": {f"{a}->{b}": n
                  for (a, b), n in sorted(_registry.edges().items())},
    }


def violations(summary: Dict[str, object]) -> List[str]:
    """Human-readable problems in a soak-end locktrace summary (the
    dict :func:`report` returns, optionally extended with
    ``leaked_threads`` and an ``oracle`` summary by the soak drivers).
    Empty list = the soak's concurrency invariants held."""
    out: List[str] = []
    for cyc in summary.get("cycles", []):     # type: ignore[union-attr]
        out.append("lock-order cycle: " + " -> ".join(cyc))
    for name in summary.get("leaked_threads", []):
        out.append(f"leaked thread/executor: {name}")
    oracle = summary.get("oracle") or {}
    for v in oracle.get("violations", []):    # type: ignore[union-attr]
        out.append(
            "workqueue double-dispatch: "
            f"{v.get('controller')} key={v.get('key')} threads "
            f"{v.get('first_thread')}/{v.get('second_thread')}")
    return out


class WorkqueueOracle:
    """Verifies the workqueue's per-key never-concurrent invariant: at
    most one in-flight reconcile per (controller, key), however many
    workers drain the pool. ``ControllerManager`` calls enter/exit
    around ``_reconcile_once`` when an oracle is installed."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._inflight: Dict[Tuple[str, Tuple[str, str]],
                             Tuple[int, List[str]]] = {}
        self.entries = 0
        self.violations: List[Dict[str, object]] = []

    def enter(self, controller: str, key: Tuple[str, str]) -> None:
        ident = threading.get_ident()
        k = (controller, tuple(key))
        with self._mu:
            self.entries += 1
            prior = self._inflight.get(k)
            if prior is not None:
                self.violations.append({
                    "controller": controller,
                    "key": list(key),
                    "first_thread": prior[0],
                    "first_stack": prior[1],
                    "second_thread": ident,
                    "second_stack": _stack(skip=3),
                })
            else:
                self._inflight[k] = (ident, _stack(skip=3))

    def exit(self, controller: str, key: Tuple[str, str]) -> None:
        k = (controller, tuple(key))
        with self._mu:
            ent = self._inflight.get(k)
            if ent is not None and ent[0] == threading.get_ident():
                del self._inflight[k]

    def clean(self) -> bool:
        return not self.violations

    def summary(self) -> Dict[str, object]:
        with self._mu:
            return {
                "entries": self.entries,
                "violations": list(self.violations),
                "inflight_now": len(self._inflight),
            }
