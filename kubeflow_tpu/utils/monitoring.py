"""Prometheus-style metrics primitives for controllers and services.

Mirrors the reference's per-controller monitoring pattern — counters with
severity labels plus a heartbeat (reference: components/profile-controller/
controllers/monitoring.go:24-78, components/notebook-controller/pkg/metrics/
metrics.go:13-21, components/access-management/kfam/monitoring.go) — without
requiring a prometheus client at runtime: the registry renders the standard
text exposition format itself, so any scraper can consume it.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Exemplar capture (ISSUE 15): the span currently open on this thread
# donates its trace id to the observed bucket. tracing is stdlib-only
# and imports nothing back from monitoring — no cycle.
from kubeflow_tpu.utils.tracing import current_span as _current_span

LabelKV = Tuple[Tuple[str, str], ...]

#: Default latency buckets (seconds). Tuned for an in-process control
#: plane: reconciles and API verbs live in the 50µs–50ms band, with the
#: tail buckets catching real-cluster RTTs and slow reconcile bodies.
#: (Chaos-injected verb latency sleeps in the PROXY, ahead of the inner
#: server's histogram — it shows up in reconcile/queue-wait/watch-lag
#: numbers, deliberately not in kftpu_apiserver_request_duration_seconds,
#: which measures the server itself.)
DEFAULT_LATENCY_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _fmt_value(v: float) -> str:
    """Full-precision float rendering (repr round-trips); '%g' would truncate
    unix timestamps to ~1000 s resolution and corrupt large counters.
    Non-finite values render in Prometheus spelling instead of crashing the
    whole scrape."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def sanitize_metric_name(part: str) -> str:
    """Make an interpolated name fragment exposition-legal. Component
    names like ``fake-kubelet`` produced ``kftpu_fake-kubelet_*`` metric
    names, which every real Prometheus scraper rejects (`-` is outside
    ``[a-zA-Z0-9_:]``) — found by the CI obs-smoke parse gate."""
    return re.sub(r"[^a-zA-Z0-9_:]", "_", part)


def _fmt_labels(labels: LabelKV) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: Dict[LabelKV, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> LabelKV:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"counter {self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            out.append(f"{self.name} 0")
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(labels)} {_fmt_value(v)}")
        return out

    def samples(self) -> List[Tuple[str, LabelKV, float]]:
        with self._lock:
            items = list(self._values.items())
        return [(self.name, labels, v) for labels, v in items]


class Gauge:
    """A settable (or callback-backed) gauge. ``label_names`` turns it into
    a labeled family: ``set(v, shard="0")`` / ``value(shard="0")`` — the
    callback form stays unlabeled (one callable, one sample)."""

    def __init__(
        self,
        name: str,
        help_: str,
        fn: Optional[Callable[[], float]] = None,
        label_names: Tuple[str, ...] = (),
    ):
        if fn is not None and label_names:
            raise ValueError(
                f"gauge {name}: callback-backed gauges cannot take labels"
            )
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._fn = fn
        self._values: Dict[LabelKV, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> LabelKV:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"gauge {self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(sorted(labels.items()))

    def set(self, v: float, **labels: str) -> None:
        if self._fn is not None:
            raise ValueError(f"gauge {self.name} is callback-backed; set() invalid")
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(v)

    def value(self, **labels: str) -> float:
        if self._fn is not None:
            return float(self._fn())
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
        ]
        if self._fn is not None or not self.label_names:
            out.append(f"{self.name} {_fmt_value(self.value())}")
            return out
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(labels)} {_fmt_value(v)}")
        return out

    def samples(self) -> List[Tuple[str, LabelKV, float]]:
        if self._fn is not None or not self.label_names:
            return [(self.name, (), self.value())]
        with self._lock:
            items = list(self._values.items())
        return [(self.name, labels, v) for labels, v in items]


class Heartbeat:
    """A gauge recording the unix time of the last explicit beat() — so a
    wedged reconcile loop shows up as a stale heartbeat even while the
    metrics endpoint keeps serving (the point of the reference's heartbeat
    goroutine, profile-controller/controllers/monitoring.go:62-78)."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._last = 0.0
        self._lock = threading.Lock()

    def beat(self) -> None:
        with self._lock:
            self._last = time.time()

    def last(self) -> float:
        with self._lock:
            return self._last

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt_value(self.last())}",
        ]

    def samples(self) -> List[Tuple[str, LabelKV, float]]:
        return [(self.name, (), self.last())]


#: Labelsets per histogram whose exemplars are retained (latest-wins per
#: band beyond this many labelsets would grow with cardinality; the cap
#: keeps the exemplar store bounded no matter what labels traffic mints).
EXEMPLAR_LABELSET_CAP = 64


class Histogram:
    """A Prometheus histogram: cumulative ``_bucket{le=...}`` counts plus
    ``_sum``/``_count``, rendered in the text exposition format.

    Buckets are the *upper bounds* of each band (ascending, finite); the
    implicit ``+Inf`` bucket is always appended, so ``_bucket{le="+Inf"}``
    equals ``_count`` by construction. ``quantile`` estimates percentiles
    by linear interpolation inside the bucket containing the rank — the
    same estimate a PromQL ``histogram_quantile`` would produce, which is
    what lets ``tpuctl top`` (scraping text) and the in-process benches
    (reading this object) report the same numbers.

    **Exemplars (ISSUE 15).** ``observe()`` captures the current trace id
    (the span open on this thread, or an explicit ``exemplar=``) per
    bucket band, latest-wins — so every percentile, and every SLO alert
    computed from these buckets, can name ONE concrete trace that landed
    in the band. Bounded: one exemplar per band per labelset, at most
    :data:`EXEMPLAR_LABELSET_CAP` labelsets; the text exposition is
    untouched (exemplars are an in-process read surface, `tpuctl slo`
    and the SLO engine read them back).
    """

    def __init__(self, name: str, help_: str,
                 label_names: Tuple[str, ...] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        bs = sorted(set(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name}: needs at least one bucket")
        if any(b != b or b in (float("inf"), float("-inf")) for b in bs):
            raise ValueError(f"histogram {name}: buckets must be finite "
                             "(+Inf is implicit)")
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.buckets = tuple(bs)
        # per-labelset state: [per-bucket counts..., +Inf count], sum
        self._counts: Dict[LabelKV, List[int]] = {}
        self._sums: Dict[LabelKV, float] = {}
        # per-labelset, per-band: (seq, trace_id, value) — latest-wins.
        self._exemplars: Dict[LabelKV, Dict[int, Tuple[int, str, float]]] = {}
        self._exemplar_seq = 0
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> LabelKV:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"histogram {self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(sorted(labels.items()))

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels: str) -> None:
        """Record one observation. ``exemplar`` optionally names the
        trace id to pin to the observation's bucket band; when omitted,
        the trace id of the span currently open on this thread (if any)
        is captured — the metric→trace edge the SLO engine resolves."""
        key = self._key(labels)
        v = float(value)
        if exemplar is None:
            span = _current_span()
            if span is not None:
                exemplar = span.trace_id
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            # Non-cumulative per-band tally internally; cumulated at render
            # so observe stays O(log b) not O(b).
            band = bisect.bisect_left(self.buckets, v)
            counts[band] += 1
            self._sums[key] += v
            if exemplar:
                ex = self._exemplars.get(key)
                if ex is None:
                    if len(self._exemplars) >= EXEMPLAR_LABELSET_CAP:
                        return
                    ex = self._exemplars[key] = {}
                self._exemplar_seq += 1
                ex[band] = (self._exemplar_seq, exemplar, v)

    def count(self, **labels: str) -> int:
        """Observation count. An exact labelset returns that series; a
        SUBSET of the label names (including none) aggregates across the
        matching family — so ``count()`` on a labeled histogram is the
        family-wide total."""
        if set(labels) != set(self.label_names):
            bands, _ = self._merged(self._subset(labels))
            return sum(bands)
        key = self._key(labels)
        with self._lock:
            return sum(self._counts.get(key, ()))

    def sum(self, **labels: str) -> float:
        """Observation sum; subset labels aggregate like :meth:`count`."""
        if set(labels) != set(self.label_names):
            _, total = self._merged(self._subset(labels))
            return total
        key = self._key(labels)
        with self._lock:
            return self._sums.get(key, 0.0)

    def _subset(self, labels: Dict[str, str]) -> Dict[str, str]:
        if not set(labels) <= set(self.label_names):
            raise ValueError(
                f"histogram {self.name} expects a subset of labels "
                f"{self.label_names}, got {tuple(sorted(labels))}")
        return labels

    # ------------- exemplars / SLI read surface (ISSUE 15) -------------

    def labelsets(self) -> List[LabelKV]:
        """Every labelset this family has observed (point-in-time copy) —
        how the SLO engine enumerates ``group_by`` series."""
        with self._lock:
            return list(self._counts.keys())

    def cumulative(self, **labels: str) -> List[Tuple[float, float]]:
        """Ascending ``(upper_bound, cumulative_count)`` pairs ending with
        the ``+Inf`` bucket, aggregated over every labelset matching the
        given label SUBSET — the SLI input the SLO engine differentiates
        between evaluations (and the same shape ``quantile_from_buckets``
        consumes)."""
        bands, _ = self._merged(self._subset(labels))
        pairs: List[Tuple[float, float]] = []
        cum = 0
        for le, c in zip(self.buckets, bands):
            cum += c
            pairs.append((le, float(cum)))
        cum += bands[-1]
        pairs.append((float("inf"), float(cum)))
        return pairs

    def exemplars(self, **labels: str) -> List[Dict[str, object]]:
        """The retained exemplars for every labelset matching the label
        subset, newest first: ``{"le", "trace_id", "value", "labels"}``
        per bucket band (latest-wins within a band)."""
        want = set(self._subset(labels).items())
        out = []
        with self._lock:
            for key, ex in self._exemplars.items():
                if not want <= set(key):
                    continue
                for band, (seq, trace_id, v) in ex.items():
                    le = (self.buckets[band] if band < len(self.buckets)
                          else float("inf"))
                    out.append({"seq": seq, "le": le, "trace_id": trace_id,
                                "value": v, "labels": dict(key)})
        out.sort(key=lambda e: -e["seq"])
        for e in out:
            del e["seq"]
        return out

    def exemplar_over(self, threshold: float,
                      **labels: str) -> Optional[Dict[str, object]]:
        """The NEWEST exemplar whose observed value exceeds ``threshold``
        — the trace a burning latency objective hands to ``tpuctl trace``
        (None when no over-threshold observation retained one)."""
        for e in self.exemplars(**labels):
            if e["value"] > threshold:
                return e
        return None

    def _merged(self, labels: Dict[str, str]) -> Tuple[List[int], float]:
        """Aggregate (band counts, sum) across every labelset matching the
        given *subset* of labels — ``quantile()`` with no labels spans the
        whole family (e.g. all controllers)."""
        want = set(labels.items())
        bands = [0] * (len(self.buckets) + 1)
        total = 0.0
        with self._lock:
            for key, counts in self._counts.items():
                if want <= set(key):
                    for i, c in enumerate(counts):
                        bands[i] += c
                    total += self._sums[key]
        return bands, total

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Estimated q-quantile (0 < q < 1) aggregated over every labelset
        matching the given label subset; None with no observations."""
        return quantile_from_buckets(self.cumulative(**labels), q)

    def percentiles(self, qs: Sequence[float] = (0.5, 0.95, 0.99),
                    **labels: str) -> Dict[str, float]:
        """{"p50": ..., "p95": ...} for the matching labelsets; empty dict
        with no observations (so JSON reports omit rather than fake)."""
        out: Dict[str, float] = {}
        for q in qs:
            v = self.quantile(q, **labels)
            if v is not None:
                # %g keying: int() float-truncates (0.29*100 -> p28) and
                # collides p99 with p99.9; %g yields p29 / p99 / p99.9.
                out[f"p{q * 100:g}"] = round(v, 6)
        return out

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            items = sorted((k, list(c), self._sums[k])
                           for k, c in self._counts.items())
        for labels, bands, total in items:
            cum = 0
            for le, c in zip(self.buckets, bands):
                cum += c
                lv = labels + (("le", _fmt_value(le)),)
                out.append(
                    f"{self.name}_bucket{_fmt_labels(lv)} {cum}")
            cum += bands[-1]
            lv = labels + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lv)} {cum}")
            out.append(
                f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
            out.append(f"{self.name}_count{_fmt_labels(labels)} {cum}")
        return out

    def samples(self) -> List[Tuple[str, LabelKV, float]]:
        with self._lock:
            items = [(k, list(c), self._sums[k])
                     for k, c in self._counts.items()]
        out: List[Tuple[str, LabelKV, float]] = []
        for labels, bands, total in items:
            cum = 0
            for le, c in zip(self.buckets, bands):
                cum += c
                out.append((f"{self.name}_bucket",
                            labels + (("le", _fmt_value(le)),), float(cum)))
            cum += bands[-1]
            out.append((f"{self.name}_bucket",
                        labels + (("le", "+Inf"),), float(cum)))
            out.append((f"{self.name}_sum", labels, total))
            out.append((f"{self.name}_count", labels, float(cum)))
        return out


def nearest_rank_quantile(values: Sequence[float], q: float) -> float:
    """Nearest-rank q-quantile of raw observations (0.0 when empty).
    The list-based sibling of :func:`quantile_from_buckets`, shared by
    the serving engine's load() ring and the serve-bench reporters so
    the index convention lives in one place."""
    if not values:
        return 0.0
    xs = sorted(values)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def quantile_from_buckets(
    pairs: Sequence[Tuple[float, float]], q: float
) -> Optional[float]:
    """Quantile estimate from cumulative histogram buckets: ``pairs`` is
    ascending ``(upper_bound, cumulative_count)`` ending with the +Inf
    bucket. Linear interpolation inside the containing bucket; observations
    past the last finite bound clamp to it (the PromQL convention). Shared
    by :meth:`Histogram.quantile` and the ``tpuctl top`` scrape parser."""
    if not 0.0 < q < 1.0:
        raise ValueError(f"quantile must be in (0, 1), got {q}")
    if not pairs:
        return None
    total = pairs[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    last_finite = 0.0
    for le, cum in pairs:
        if le != float("inf"):
            last_finite = le
        if cum >= rank:
            if le == float("inf"):
                return last_finite if last_finite else prev_le
            span = cum - prev_cum
            if span <= 0:
                return le
            return prev_le + (le - prev_le) * (rank - prev_cum) / span
        prev_le, prev_cum = le, cum
    return last_finite


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r'\\(.)')
_UNESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label_value(v: str) -> str:
    # Single-pass inverse of _escape_label_value: sequential str.replace
    # corrupted values like 'C:\\new' (the escaped backslash's output fed
    # the \n replacement).
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), m.group(0)), v)


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse the Prometheus text exposition format back into
    ``(name, labels, value)`` samples — the consumer half of ``render()``,
    used by ``tpuctl top`` and the CI obs-smoke scrape assertion."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, raw_labels, raw_value = m.groups()
        labels: Dict[str, str] = {}
        if raw_labels:
            for lm in _LABEL_RE.finditer(raw_labels):
                labels[lm.group(1)] = _unescape_label_value(lm.group(2))
        v = raw_value
        if v == "+Inf":
            value = float("inf")
        elif v == "-Inf":
            value = float("-inf")
        else:
            value = float(v)
        out.append((name, labels, value))
    return out


class MetricsRegistry:
    """Holds metrics and renders the text exposition format. Metric names are
    unique per registry; registering an existing name returns the existing
    instance (so two controllers sharing the global registry don't produce a
    duplicate-TYPE scrape that Prometheus rejects)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, factory: Callable[[], object]):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                return existing
            m = factory()
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str, labels: Tuple[str, ...] = ()) -> Counter:
        m = self._register(name, lambda: Counter(name, help_, labels))
        if not isinstance(m, Counter):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def gauge(
        self, name: str, help_: str,
        fn: Optional[Callable[[], float]] = None,
        labels: Tuple[str, ...] = (),
    ) -> Gauge:
        m = self._register(name, lambda: Gauge(name, help_, fn, labels))
        if not isinstance(m, Gauge):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def histogram(
        self, name: str, help_: str,
        labels: Tuple[str, ...] = (),
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> Histogram:
        m = self._register(name, lambda: Histogram(name, help_, labels, buckets))
        if not isinstance(m, Histogram):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def get(self, name: str) -> Optional[object]:
        """The registered metric object by name, or None — benches read
        their histograms back this way instead of re-plumbing references."""
        with self._lock:
            return self._metrics.get(name)

    def percentiles(self, name: str,
                    qs: Sequence[float] = (0.5, 0.95, 0.99),
                    **labels: str) -> Dict[str, float]:
        """p50/p95/p99 dict for a registered histogram (empty when the
        metric is missing, not a histogram, or has no observations) — the
        one lookup the bench and soak reports share."""
        h = self.get(name)
        if not isinstance(h, Histogram):
            return {}
        return h.percentiles(qs, **labels)

    def heartbeat(self, component: str) -> Heartbeat:
        name = f"kftpu_{sanitize_metric_name(component)}_heartbeat"
        m = self._register(
            name, lambda: Heartbeat(name, f"Unix time of last {component} heartbeat")
        )
        if not isinstance(m, Heartbeat):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"

    def snapshot(self) -> List[Tuple[str, LabelKV, float]]:
        """Point-in-time (name, labels, value) samples for EVERY registered
        metric — the stable read surface for samplers (the time-series
        collector) that must not race concurrent registration. Duck-typed
        through each metric's ``samples()`` so new metric types (and the
        Heartbeat / labeled-gauge families an isinstance ladder silently
        dropped) can never fall out of the sample stream again."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: List[Tuple[str, LabelKV, float]] = []
        for m in metrics:
            out.extend(m.samples())  # type: ignore[attr-defined]
        return out


class MetricsHttpServer:
    """Serve a registry's Prometheus text exposition over HTTP (the
    scrape endpoint every long-lived platform process exposes)."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0"):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = reg.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.httpd.shutdown()


global_registry = MetricsRegistry()
