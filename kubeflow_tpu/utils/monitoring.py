"""Prometheus-style metrics primitives for controllers and services.

Mirrors the reference's per-controller monitoring pattern — counters with
severity labels plus a heartbeat (reference: components/profile-controller/
controllers/monitoring.go:24-78, components/notebook-controller/pkg/metrics/
metrics.go:13-21, components/access-management/kfam/monitoring.go) — without
requiring a prometheus client at runtime: the registry renders the standard
text exposition format itself, so any scraper can consume it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

LabelKV = Tuple[Tuple[str, str], ...]


def _fmt_value(v: float) -> str:
    """Full-precision float rendering (repr round-trips); '%g' would truncate
    unix timestamps to ~1000 s resolution and corrupt large counters.
    Non-finite values render in Prometheus spelling instead of crashing the
    whole scrape."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: LabelKV) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: Dict[LabelKV, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> LabelKV:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"counter {self.name} expects labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            out.append(f"{self.name} 0")
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(labels)} {_fmt_value(v)}")
        return out


class Gauge:
    def __init__(
        self,
        name: str,
        help_: str,
        fn: Optional[Callable[[], float]] = None,
    ):
        self.name = name
        self.help = help_
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        if self._fn is not None:
            raise ValueError(f"gauge {self.name} is callback-backed; set() invalid")
        with self._lock:
            self._value = v

    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._lock:
            return self._value

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt_value(self.value())}",
        ]


class Heartbeat:
    """A gauge recording the unix time of the last explicit beat() — so a
    wedged reconcile loop shows up as a stale heartbeat even while the
    metrics endpoint keeps serving (the point of the reference's heartbeat
    goroutine, profile-controller/controllers/monitoring.go:62-78)."""

    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._last = 0.0
        self._lock = threading.Lock()

    def beat(self) -> None:
        with self._lock:
            self._last = time.time()

    def last(self) -> float:
        with self._lock:
            return self._last

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt_value(self.last())}",
        ]


class MetricsRegistry:
    """Holds metrics and renders the text exposition format. Metric names are
    unique per registry; registering an existing name returns the existing
    instance (so two controllers sharing the global registry don't produce a
    duplicate-TYPE scrape that Prometheus rejects)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, factory: Callable[[], object]):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                return existing
            m = factory()
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str, labels: Tuple[str, ...] = ()) -> Counter:
        m = self._register(name, lambda: Counter(name, help_, labels))
        if not isinstance(m, Counter):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def gauge(
        self, name: str, help_: str, fn: Optional[Callable[[], float]] = None
    ) -> Gauge:
        m = self._register(name, lambda: Gauge(name, help_, fn))
        if not isinstance(m, Gauge):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def heartbeat(self, component: str) -> Heartbeat:
        name = f"kftpu_{component}_heartbeat"
        m = self._register(
            name, lambda: Heartbeat(name, f"Unix time of last {component} heartbeat")
        )
        if not isinstance(m, Heartbeat):
            raise ValueError(f"metric {name} already registered as {type(m).__name__}")
        return m

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"

    def snapshot(self) -> List[Tuple[str, LabelKV, float]]:
        """Point-in-time (name, labels, value) samples for every counter
        and gauge — the stable read surface for samplers (the time-series
        collector) that must not race concurrent registration."""
        with self._lock:
            metrics = list(self._metrics.items())
        out: List[Tuple[str, LabelKV, float]] = []
        for name, m in metrics:
            if isinstance(m, Counter):
                with m._lock:
                    items = list(m._values.items())
                out.extend((name, labels, v) for labels, v in items)
            elif isinstance(m, Gauge):
                out.append((name, (), m.value()))
        return out


class MetricsHttpServer:
    """Serve a registry's Prometheus text exposition over HTTP (the
    scrape endpoint every long-lived platform process exposes)."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0"):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = reg.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.httpd.shutdown()


global_registry = MetricsRegistry()
