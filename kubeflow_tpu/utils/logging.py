"""Structured logging for controllers and training.

The reference uses logr/zap in Go controllers (components/notebook-controller/
main.go) and a `create_logger` helper in Python (components/jupyter-web-app/
backend/kubeflow_jupyter/common/utils.py:34). We provide one structured
logger factory with key=value context, shared by the control plane and the
training runtime.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Any


class _KVAdapter(logging.LoggerAdapter):
    def process(self, msg: str, kwargs: Any):
        extra = kwargs.pop("kv", None) or {}
        bound = self.extra or {}
        merged = {**bound, **extra}
        if merged:
            kv = " ".join(f"{k}={v}" for k, v in merged.items())
            msg = f"{msg} {kv}"
        return msg, kwargs

    def bind(self, **kv: Any) -> "_KVAdapter":
        return _KVAdapter(self.logger, {**(self.extra or {}), **kv})


_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("KFTPU_LOG_LEVEL", "INFO").strip().upper()
    if level not in ("CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG"):
        level = "INFO"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s %(message)s")
    )
    root = logging.getLogger("kubeflow_tpu")
    root.setLevel(level)
    if not root.handlers:
        root.addHandler(handler)
    root.propagate = False
    _configured = True


def get_logger(name: str, **kv: Any) -> _KVAdapter:
    _configure_root()
    return _KVAdapter(logging.getLogger(f"kubeflow_tpu.{name}"), kv)
