"""Structured logging for controllers and training.

The reference uses logr/zap in Go controllers (components/notebook-controller/
main.go) and a `create_logger` helper in Python (components/jupyter-web-app/
backend/kubeflow_jupyter/common/utils.py:34). We provide one structured
logger factory with key=value context, shared by the control plane and the
training runtime.

``KFTPU_LOG_FORMAT=json`` switches the root handler to one-JSON-object-per-
line output, and every record is stamped with the current ``trace_id``/
``span_id`` from the in-process tracer (utils/tracing.py) when a span is
open — the log↔trace correlation that lets ``tpuctl trace`` output be
joined against controller logs. The text format stays the default.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Any

_json_mode = False


class _KVAdapter(logging.LoggerAdapter):
    def process(self, msg: str, kwargs: Any):
        extra = kwargs.pop("kv", None) or {}
        bound = self.extra or {}
        merged = {**bound, **extra}
        if _json_mode:
            # Structured output: hand the kv dict to the formatter via the
            # record instead of flattening it into the message string.
            kwargs.setdefault("extra", {})["kftpu_kv"] = merged
        elif merged:
            kv = " ".join(f"{k}={v}" for k, v in merged.items())
            msg = f"{msg} {kv}"
        return msg, kwargs

    def bind(self, **kv: Any) -> "_KVAdapter":
        return _KVAdapter(self.logger, {**(self.extra or {}), **kv})


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        kv = getattr(record, "kftpu_kv", None)
        if kv:
            out.update({str(k): _jsonable(v) for k, v in kv.items()})
        # Correlate with the active trace, when one is open on this
        # thread — whichever Tracer instance opened it (Platform and the
        # benches run private tracers; the current-span context is
        # process-wide).
        from kubeflow_tpu.utils.tracing import current_span

        span = current_span()
        if span is not None:
            out["trace_id"] = span.trace_id
            out["span_id"] = span.span_id
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


_configured = False
_our_handler: "logging.Handler | None" = None


def configure(force: bool = False) -> None:
    """(Re-)configure the ``kubeflow_tpu`` root logger from the
    environment: ``KFTPU_LOG_LEVEL`` and ``KFTPU_LOG_FORMAT`` (``text`` |
    ``json``). ``force`` re-reads the env and swaps OUR handler — how
    tests and long-lived services switch format at runtime. Handlers an
    embedding application pre-installed are always left alone: the
    implicit first call adds ours only when none exist, and force only
    ever replaces the handler this module installed."""
    global _configured, _json_mode, _our_handler
    if _configured and not force:
        return
    level = os.environ.get("KFTPU_LOG_LEVEL", "INFO").strip().upper()
    if level not in ("CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG"):
        level = "INFO"
    _json_mode = (
        os.environ.get("KFTPU_LOG_FORMAT", "text").strip().lower() == "json"
    )
    handler = logging.StreamHandler(sys.stderr)
    if _json_mode:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s %(message)s")
        )
    root = logging.getLogger("kubeflow_tpu")
    root.setLevel(level)
    had_ours = _our_handler is not None
    if had_ours:
        root.removeHandler(_our_handler)
        _our_handler = None
    # Install ours only when replacing our own or when no handler exists;
    # force never ADDS next to an embedding app's handler (that would
    # duplicate every line).
    if had_ours or not root.handlers:
        root.addHandler(handler)
        _our_handler = handler
    # kv routing must match the handler that will render it: json mode is
    # only honoured when OUR json handler is actually installed —
    # otherwise a foreign handler would silently drop record.kftpu_kv.
    _json_mode = _json_mode and _our_handler is handler
    root.propagate = False
    _configured = True


def get_logger(name: str, **kv: Any) -> _KVAdapter:
    configure()
    return _KVAdapter(logging.getLogger(f"kubeflow_tpu.{name}"), kv)
