"""Fleet SLO engine: declarative objectives, burn-rate alerts (ISSUE 15).

The platform *records* everything — verb/reconcile/watch-lag histograms
(PR 4), the goodput ledger (PR 10), tenant SLO burn (PR 13) — but until
now nothing *watched* it. This module is the detect-and-explain layer:

- **Objectives** (:class:`Objective`) are declarative SLIs over the
  metrics registry: a latency histogram + threshold ("99% of admissions
  under 250ms"), a gauge family ("every tenant's goodput ratio >= 0.5",
  one series per ``tenant`` label), or an arbitrary value source (the
  goodput ledger's interruption delta). ``group_by`` fans one objective
  out per label value — the starvation objective watches
  ``kftpu_scheduler_queue_age_seconds`` per ``priority`` class.

- **Multi-window burn rates**: each evaluation appends one
  ``(t, good, bad)`` sample per series; burn over a window is the bad
  fraction divided by the error budget ``(1 - slo)``. Four windows — a
  fast pair (5m/1h real time) and a slow pair (6h/3d) — follow the SRE
  multi-window discipline: the fast pair must BOTH burn past
  ``page_burn`` to page (a blip in one window cannot), the slow pair
  past ``warn_burn`` to warn. Windows are declarative seconds in live
  runs and tick-scaled (:data:`TICK_WINDOWS`) in benches/soaks, so the
  same state machine is deterministic under seeded ticks.

- **Alert state machine** with hysteresis: escalation (ok→warn→page) is
  immediate when the condition holds; de-escalation requires
  ``clear_after`` consecutive quiet evaluations — a series flapping
  across its threshold holds its state instead of re-paging every tick.
  Every transition is journaled to ``alerts.jsonl`` (fsync'd, the
  goodput-ledger/WAL discipline) and :meth:`SLOEngine.replay_from`
  rebuilds states/counters byte-identically through the same apply path
  — a SIGKILLed shard's engine comes back with an identical
  :meth:`fingerprint`. The journal rotates with the single-generation
  rollover (state-record head, both generations replayed).

- **Exemplars**: histogram-backed objectives resolve their alert to the
  newest over-threshold exemplar the histogram retained
  (``Histogram.exemplar_over`` — the trace id captured at observe
  time), so a fired alert carries the concrete trace ``tpuctl trace``
  renders into the write→watch→reconcile (or submit→admit→decode)
  causal timeline.

- **Flight-recorder triggers**: a page transition (and any registered
  guard flipping false — the goodput conservation gate) dumps the
  attached :class:`~kubeflow_tpu.obs.flight.FlightRecorder` ring.

Surfaces: ``tpuctl slo`` scoreboard (rc 3 on any page),
``kftpu_slo_burn_rate{objective,window}`` gauges and
``kftpu_alerts_total{objective,state}`` counters, plus ``slo`` sections
in soak/storm reports. CI gates both directions in ``slo-smoke``: a
clean seeded soak fires ZERO alerts, the fault-injected soak fires the
expected objective set exactly once each (docs/observability.md).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.obs.goodput import JOURNAL_ROTATE_BYTES, _Journal
from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import Gauge, Histogram, MetricsRegistry

log = get_logger("slo")

ALERTS_JOURNAL = "alerts.jsonl"

#: Alert severities, escalation order.
ALERT_STATES = ("ok", "warn", "page")
_RANK = {s: i for i, s in enumerate(ALERT_STATES)}


@dataclasses.dataclass(frozen=True)
class Windows:
    """The four burn-rate windows (seconds — or ticks, in tick-driven
    drivers; the engine never converts, the caller picks the unit its
    ``evaluate(now)`` clock uses)."""

    fast_short: float = 300.0        # 5m
    fast_long: float = 3600.0        # 1h
    slow_short: float = 21600.0      # 6h
    slow_long: float = 259200.0      # 3d

    def items(self) -> Tuple[Tuple[str, float], ...]:
        return (("fast_short", self.fast_short),
                ("fast_long", self.fast_long),
                ("slow_short", self.slow_short),
                ("slow_long", self.slow_long))

    @property
    def longest(self) -> float:
        return max(self.fast_short, self.fast_long,
                   self.slow_short, self.slow_long)


#: Real-time production windows.
DEFAULT_WINDOWS = Windows()

#: Tick-scaled windows for seeded soaks/benches (one evaluation per
#: driver tick): short enough that a 40-round soak exercises the whole
#: state machine, long enough that one startup tick cannot page.
TICK_WINDOWS = Windows(fast_short=3.0, fast_long=6.0,
                       slow_short=9.0, slow_long=18.0)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative SLO. Exactly one SLI source must be set:

    - ``metric``: a histogram name; an event is GOOD when its observed
      value <= ``threshold_s`` (the latency contract);
    - ``gauge``: a gauge(-family) name; each evaluation samples every
      series, GOOD when the value sits inside [min_value, max_value];
    - ``value_fn``: an arbitrary callable; None = no sample this round.

    ``slo`` is the target good fraction — the error budget is
    ``1 - slo``. ``group_by`` fans the objective out per label value
    (series key ``name[label=value]``)."""

    name: str
    description: str = ""
    metric: str = ""
    threshold_s: float = 0.0
    gauge: str = ""
    value_fn: Optional[Callable[[], Optional[float]]] = None
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    group_by: str = ""
    slo: float = 0.99
    page_burn: float = 14.4
    warn_burn: float = 2.0
    windows: Windows = DEFAULT_WINDOWS
    clear_after: int = 3

    def __post_init__(self):
        sources = sum(1 for s in (self.metric, self.gauge,
                                  self.value_fn) if s)
        if sources != 1:
            raise ValueError(
                f"objective {self.name!r}: exactly one of metric/gauge/"
                f"value_fn must be set, got {sources}")
        if not 0.0 < self.slo < 1.0:
            raise ValueError(
                f"objective {self.name!r}: slo must be in (0, 1), "
                f"got {self.slo}")
        if self.value_fn is not None and self.group_by:
            raise ValueError(
                f"objective {self.name!r}: group_by needs a metric/gauge "
                "family to enumerate")

    def good_value(self, v: float) -> bool:
        if self.min_value is not None and v < self.min_value:
            return False
        if self.max_value is not None and v > self.max_value:
            return False
        return True


class _Series:
    """Evaluation state for one (objective, group) series."""

    __slots__ = ("key", "base", "labels", "samples", "prev_good",
                 "prev_total", "state", "calm", "transitions", "pages",
                 "burns", "exemplar", "last_t")

    def __init__(self, key: str, base: str):
        self.key = key
        self.base = base
        self.labels: Dict[str, str] = {}   # the group_by filter, if any
        self.samples: "deque[Tuple[float, int, int]]" = deque()
        self.prev_good: Optional[float] = None
        self.prev_total: Optional[float] = None
        self.state = "ok"
        self.calm = 0                # consecutive quiet evaluations
        self.transitions = 0
        self.pages = 0
        self.burns: Dict[str, Optional[float]] = {}
        self.exemplar = ""           # trace id of the last transition
        self.last_t = 0.0


class SLOEngine:
    """Evaluates a set of :class:`Objective` s against a metrics
    registry, runs the alert state machine, journals transitions.

    ``evaluate(now)`` is the one clock input: monotone seconds live
    (``time.monotonic()``), integer ticks in seeded drivers — windows
    are in the same unit. Deterministic: same metric/tick sequence, same
    transitions, byte-identical journal."""

    def __init__(
        self,
        registry: MetricsRegistry,
        *,
        objectives: List[Objective],
        journal_path: str = "",
        fsync: bool = True,
        rotate_bytes: int = JOURNAL_ROTATE_BYTES,
        recorder=None,                  # obs.flight.FlightRecorder
        dump_dir: str = "",             # flight dumps land here on page
        max_samples: int = 8192,
    ):
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self.registry = registry
        self.objectives: Dict[str, Objective] = {
            o.name: o for o in objectives}
        self.recorder = recorder
        self.dump_dir = dump_dir
        self.max_samples = int(max_samples)
        self.guards: Dict[str, Callable[[], bool]] = {}
        self._series: Dict[str, _Series] = {}
        self._journal = _Journal(journal_path, fsync)
        self._rotate_bytes = int(rotate_bytes)
        self._replaying = False
        self._lock = threading.RLock()
        self.metrics_burn = registry.gauge(
            "kftpu_slo_burn_rate",
            "Error-budget burn rate per objective series and window "
            "(bad fraction over the window / (1 - slo))",
            labels=("objective", "window"),
        )
        self.metrics_alerts = registry.counter(
            "kftpu_alerts_total",
            "Alert state transitions per objective series, labeled by "
            "the state ENTERED",
            labels=("objective", "state"),
        )

    # ----------------- wiring -----------------

    def add_guard(self, name: str, fn: Callable[[], bool]) -> None:
        """Register an invariant (True = healthy) checked every
        evaluation; the FIRST False records + dumps the flight ring
        (latched per guard — see FlightRecorder.check_guards)."""
        self.guards[name] = fn

    def rebaseline_sources(self) -> int:
        """Re-anchor every value source that supports it (closures
        carrying a ``rebaseline`` attribute) — called after persisted
        state is restored INTO an already-built source, so history does
        not read as a fresh delta. Returns sources re-anchored."""
        n = 0
        for obj in self.objectives.values():
            hook = getattr(obj.value_fn, "rebaseline", None)
            if hook is not None:
                hook()
                n += 1
        return n

    def set_journal(self, path: str, *, replay: bool = True) -> int:
        """(Re)attach the alert journal — the platform wires this once
        it knows its state dir. ``replay`` first rebuilds state from any
        existing generations through the same apply path."""
        with self._lock:
            n = self.replay_from(path) if replay else 0
            self._journal.close()
            self._journal = _Journal(path, self._journal.fsync)
            return n

    # ----------------- measurement -----------------

    def _series_for(self, key: str, base: str) -> _Series:
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _Series(key, base)
        return s

    def _measure(self, obj: Objective) -> List[Tuple[str, int, int]]:
        """This evaluation's ``(series_key, good, bad)`` samples for one
        objective — [] when the source has no data yet (window-restart
        semantics: no sample is not a good sample)."""
        if obj.value_fn is not None:
            v = obj.value_fn()
            if v is None:
                return []
            good = obj.good_value(float(v))
            return [(obj.name, 1 if good else 0, 0 if good else 1)]
        if obj.gauge:
            g = self.registry.get(obj.gauge)
            if not isinstance(g, Gauge):
                return []
            out = []
            for _name, labels, value in sorted(g.samples()):
                ld = dict(labels)
                if obj.group_by:
                    gv = ld.get(obj.group_by)
                    if gv is None:
                        continue
                    key = f"{obj.name}[{obj.group_by}={gv}]"
                else:
                    key = obj.name
                good = obj.good_value(float(value))
                out.append((key, 1 if good else 0, 0 if good else 1))
            return out
        h = self.registry.get(obj.metric)
        if not isinstance(h, Histogram):
            return []
        groups: List[Tuple[str, Dict[str, str]]] = []
        if obj.group_by:
            values = sorted({
                dict(ls).get(obj.group_by)
                for ls in h.labelsets()
            } - {None})
            groups = [(f"{obj.name}[{obj.group_by}={v}]",
                       {obj.group_by: v}) for v in values]
        else:
            groups = [(obj.name, {})]
        out = []
        # Largest finite bucket bound <= threshold: observations at or
        # under it are the GOOD events (band granularity — thresholds
        # should sit on bucket bounds for exactness).
        idx = bisect.bisect_right(h.buckets, obj.threshold_s) - 1
        for key, flt in groups:
            pairs = h.cumulative(**flt)
            total = pairs[-1][1]
            good_cum = pairs[idx][1] if idx >= 0 else 0.0
            s = self._series_for(key, obj.name)
            s.labels = flt
            if s.prev_total is None:
                # Baseline sighting: history before the engine attached
                # is not this engine's SLI window.
                s.prev_good, s.prev_total = good_cum, total
                continue
            d_total = total - s.prev_total
            d_good = good_cum - s.prev_good
            s.prev_good, s.prev_total = good_cum, total
            if d_total <= 0:
                continue            # no events since last evaluation
            d_good = max(0.0, min(d_good, d_total))
            out.append((key, int(d_good), int(d_total - d_good)))
        return out

    def _window_burns(self, obj: Objective, s: _Series,
                      now: float) -> Dict[str, Optional[float]]:
        """All four windows' burns in ONE reverse traversal of the
        sample deque (this rides every Platform.reconcile() pass; four
        separate scans of an 8k-sample window per series added up)."""
        items = sorted(obj.windows.items(), key=lambda kv: kv[1])
        sums = {w: [0, 0] for w, _ in items}       # window -> [good, bad]
        budget = 1.0 - obj.slo
        good = bad = 0
        i = 0
        for t, g, b in reversed(s.samples):
            age = now - t
            while i < len(items) and age >= items[i][1]:
                # This sample ages out of the i-th (shortest-first)
                # window: freeze that window's sums.
                sums[items[i][0]] = [good, bad]
                i += 1
            if i >= len(items):
                break
            good += g
            bad += b
        for w, _span in items[i:]:
            sums[w] = [good, bad]
        return {
            w: ((b / (g + b)) / budget if (g + b) > 0 else None)
            for w, (g, b) in sums.items()
        }

    def _exemplar_for(self, obj: Objective, s: _Series) -> str:
        """The newest over-threshold exemplar trace id a burning
        histogram objective retained, scoped to THIS series' group
        labels — a grouped alert must not hand the operator a trace
        from a sibling group's blip ("" for value/gauge objectives)."""
        if not obj.metric:
            return ""
        h = self.registry.get(obj.metric)
        if not isinstance(h, Histogram):
            return ""
        ex = h.exemplar_over(obj.threshold_s, **s.labels)
        return str(ex["trace_id"]) if ex else ""

    # ----------------- evaluation -----------------

    def evaluate(self, now: float) -> List[dict]:
        """One evaluation pass: sample every objective, age the windows,
        run the state machine. Returns the transitions fired (already
        journaled / recorded / dumped)."""
        with self._lock:
            now = float(now)
            for obj in self.objectives.values():
                for key, good, bad in self._measure(obj):
                    s = self._series_for(key, obj.name)
                    s.samples.append((now, good, bad))
                    while len(s.samples) > self.max_samples:
                        s.samples.popleft()
            fired: List[dict] = []
            for key in sorted(self._series):
                s = self._series[key]
                obj = self.objectives.get(s.base)
                if obj is None:
                    continue        # replayed series of a retired objective
                cutoff = now - obj.windows.longest
                while s.samples and s.samples[0][0] <= cutoff:
                    s.samples.popleft()
                burns = self._window_burns(obj, s, now)
                s.burns = burns
                for wname, b in burns.items():
                    self.metrics_burn.set(
                        b if b is not None else 0.0,
                        objective=key, window=wname)
                page = all(
                    burns[w] is not None and burns[w] >= obj.page_burn
                    for w in ("fast_short", "fast_long"))
                warn = all(
                    burns[w] is not None and burns[w] >= obj.warn_burn
                    for w in ("slow_short", "slow_long"))
                target = "page" if page else ("warn" if warn else "ok")
                rec = self._step(obj, s, target, now)
                if rec is not None:
                    fired.append(rec)
            if self.guards and self.recorder is not None:
                for g in self.recorder.check_guards(self.guards,
                                                    self.dump_dir):
                    log.error("slo guard tripped", kv={"guard": g})
            return fired

    def _step(self, obj: Objective, s: _Series, target: str,
              now: float) -> Optional[dict]:
        """Hysteresis state machine: escalate immediately, de-escalate
        only after ``clear_after`` consecutive quiet evaluations."""
        new = None
        if _RANK[target] > _RANK[s.state]:
            new = target
            s.calm = 0
        elif _RANK[target] < _RANK[s.state]:
            s.calm += 1
            if s.calm >= obj.clear_after:
                new = target
                s.calm = 0
        else:
            s.calm = 0
        if new is None or new == s.state:
            return None
        exemplar = (self._exemplar_for(obj, s)
                    if _RANK[new] > 0 else s.exemplar)
        rec = {
            "op": "alert",
            "t": round(now, 6),
            "objective": s.key,
            "base": s.base,
            "from": s.state,
            "to": new,
            "burn": {w: (round(b, 4) if b is not None else None)
                     for w, b in s.burns.items()},
            "exemplar": exemplar,
        }
        self._journal_rec(rec)
        self._apply_alert(rec)
        if self.recorder is not None:
            # No explicit t: the recorder's own clock keeps the ring in
            # one domain (tick drivers hand their logical clock to the
            # FlightRecorder, live platforms stay wall-clock).
            self.recorder.record("alert", {
                "objective": s.key, "from": rec["from"], "to": new,
                "burn": rec["burn"]}, trace_id=exemplar)
            if new == "page" and self.dump_dir:
                self.recorder.dump(self.dump_dir,
                                   reason=f"alert-page:{s.key}")
        log.warning("slo alert transition", kv={
            "objective": s.key, "from": rec["from"], "to": new,
            "exemplar": exemplar or "-",
        })
        return rec

    # ----------------- journal / replay -----------------

    def _journal_rec(self, rec: dict) -> None:
        if self._replaying:
            return
        # Rotate BEFORE appending (see goodput._Journal.maybe_rotate):
        # the state head then covers the rotated generation exactly.
        if rec.get("op") != "state" \
                and self._journal.maybe_rotate(self._rotate_bytes):
            self._journal.append({"op": "state", "series":
                                  self._state_dict()})
        self._journal.append(rec)

    def _state_dict(self) -> Dict[str, dict]:
        return {
            key: {"base": s.base, "state": s.state,
                  "transitions": s.transitions, "pages": s.pages,
                  "exemplar": s.exemplar, "t": s.last_t}
            for key, s in sorted(self._series.items())
        }

    def _apply_alert(self, rec: dict) -> None:
        s = self._series_for(rec["objective"],
                             rec.get("base", rec["objective"]))
        s.state = rec["to"]
        s.transitions += 1
        s.last_t = float(rec.get("t", 0.0))
        if rec.get("exemplar"):
            s.exemplar = rec["exemplar"]
        if rec["to"] == "page":
            s.pages += 1
        self.metrics_alerts.inc(objective=s.key, state=rec["to"])

    def _apply_state(self, rec: dict) -> None:
        for key, st in rec.get("series", {}).items():
            s = self._series_for(key, st.get("base", key))
            s.state = st.get("state", "ok")
            s.transitions = int(st.get("transitions", 0))
            s.pages = int(st.get("pages", 0))
            s.exemplar = st.get("exemplar", "")
            s.last_t = float(st.get("t", 0.0))

    def replay_from(self, journal_path: str) -> int:
        """Rebuild alert state by re-applying the journal through the
        SAME apply path the live engine used (byte-identical
        ``fingerprint()`` — the shard-SIGKILL gate). Reads both rotated
        generations; replaying our OWN journal then compacts it to one
        state record."""
        recs = _Journal.read_generations(journal_path)
        with self._lock:
            self._replaying = True
            try:
                for rec in recs:
                    op = rec.get("op")
                    if op == "alert":
                        self._apply_alert(rec)
                    elif op == "state":
                        self._apply_state(rec)
            finally:
                self._replaying = False
            if recs and journal_path == self._journal.path:
                self._journal.close()
                _Journal.compact(journal_path,
                                 {"op": "state",
                                  "series": self._state_dict()})
        if recs:
            log.info("alert journal replayed",
                     kv={"records": len(recs)})
        return len(recs)

    def close(self) -> None:
        self._journal.close()

    # ----------------- read surfaces -----------------

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {key: s.state for key, s in sorted(self._series.items())}

    def pages_by_objective(self) -> Dict[str, int]:
        """Objective (base) name -> page transitions fired, grouped
        series summed — the count the slo-smoke gates compare."""
        out: Dict[str, int] = {}
        with self._lock:
            for s in self._series.values():
                if s.pages:
                    out[s.base] = out.get(s.base, 0) + s.pages
        return out

    def transitions_total(self) -> int:
        with self._lock:
            return sum(s.transitions for s in self._series.values())

    def any_paging(self) -> bool:
        with self._lock:
            return any(s.state == "page" for s in self._series.values())

    def fingerprint(self) -> str:
        """Order-independent digest over the JOURNAL-DERIVED state (per
        transitioned series: state, transition/page counts, exemplar) —
        what the shard-SIGKILL replay gate compares pre/post. Series
        that never transitioned carry no journal-observable state and
        are excluded (a replayed engine hasn't re-measured them yet)."""
        with self._lock:
            rows = sorted(
                f"{k}|{s.base}|{s.state}|{s.transitions}|{s.pages}|"
                f"{s.exemplar}|{s.last_t}"
                for k, s in self._series.items() if s.transitions > 0)
        return hashlib.sha256("\n".join(rows).encode()).hexdigest()

    def snapshot(self) -> Dict[str, Any]:
        """The scoreboard: every series with its burns, state, counts,
        exemplar — plus objective metadata and totals."""
        with self._lock:
            series: Dict[str, Any] = {}
            for key in sorted(self._series):
                s = self._series[key]
                obj = self.objectives.get(s.base)
                series[key] = {
                    "objective": s.base,
                    "slo": obj.slo if obj else None,
                    "state": s.state,
                    "burn": {w: (round(b, 4) if b is not None else None)
                             for w, b in s.burns.items()},
                    "transitions": s.transitions,
                    "pages": s.pages,
                    "exemplar": s.exemplar,
                    "samples": len(s.samples),
                }
            return {
                "series": series,
                "objectives": {
                    name: {"description": o.description, "slo": o.slo,
                           "source": o.metric or o.gauge or "value_fn",
                           "threshold_s": o.threshold_s,
                           "page_burn": o.page_burn,
                           "warn_burn": o.warn_burn}
                    for name, o in sorted(self.objectives.items())
                },
                "transitions": self.transitions_total(),
                "pages": self.pages_by_objective(),
                "paging": sorted(k for k, s in self._series.items()
                                 if s.state == "page"),
                "fingerprint": self.fingerprint(),
            }


# --------------------------------------------------------------------------
# Stock objective sets
# --------------------------------------------------------------------------


def interruption_delta_source(accountant) -> Callable[[], Optional[float]]:
    """Per-evaluation delta of the goodput ledger's interruption tally:
    0.0 on a clean interval, >0 when a preemption/migration/restart
    landed since the last evaluation. The ``max_value=0`` objective over
    it is the deterministic goodput SLI the soaks page on (a cumulative
    ratio dips too slowly to alert on, and per-tick ratios misread
    normal gang startup as badput)."""
    # Baseline NOW, not on first call: a respawned shard's first
    # evaluation may coincide with the first post-replay interruption —
    # a first-call baseline would swallow exactly that bump (found by
    # the sharded slo-smoke probe).
    state = {"prev": sum(accountant.interruptions.values())}

    def fn() -> Optional[float]:
        cur = sum(accountant.interruptions.values())
        prev = state["prev"]
        state["prev"] = cur
        return float(cur - prev)

    def rebaseline() -> None:
        state["prev"] = sum(accountant.interruptions.values())

    # Platform.load restores the ledger's persisted tallies AFTER the
    # engine (and this closure) exist — rebaseline_sources() re-anchors
    # so restored history never reads as a fresh interruption burst.
    fn.rebaseline = rebaseline
    return fn


def default_objectives(*, goodput=None,
                       windows: Windows = DEFAULT_WINDOWS,
                       ) -> List[Objective]:
    """The platform's stock fleet objectives (docs/observability.md
    carries the table). Objectives whose source metric never appears
    (no scheduler, no serving engine in-process) stay silently quiet —
    no data is not an alert."""
    objs = [
        Objective(
            name="admission-latency",
            description="99% of apiserver verbs complete within 250ms",
            metric="kftpu_apiserver_request_duration_seconds",
            threshold_s=0.25, slo=0.99, windows=windows),
        Objective(
            name="watch-delivery-lag",
            description="95% of watch events drain within 1s of their "
                        "write",
            metric="kftpu_watch_delivery_lag_seconds",
            threshold_s=1.0, slo=0.95, windows=windows),
        Objective(
            name="time-to-placement",
            description="90% of gangs place within 30s of admission",
            metric="kftpu_scheduler_time_to_place_seconds",
            threshold_s=30.0, slo=0.90, windows=windows),
        Objective(
            name="queue-age",
            description="starvation: 90% of blocked placement attempts "
                        "observe a queue age under 30min, per priority "
                        "class (the ROADMAP item-3 aging signal)",
            metric="kftpu_scheduler_queue_age_seconds",
            threshold_s=1800.0, slo=0.90, group_by="priority",
            windows=windows),
        Objective(
            name="serving-ttft",
            description="95% of requests see their first token within "
                        "500ms",
            metric="kftpu_serving_ttft_seconds",
            threshold_s=0.5, slo=0.95, windows=windows),
        Objective(
            name="serving-queue-wait",
            description="95% of admitted requests wait under 250ms for "
                        "a slot",
            metric="kftpu_serving_queue_wait_seconds",
            threshold_s=0.25, slo=0.95, windows=windows),
        Objective(
            name="tenant-goodput",
            description="every tenant's rollup goodput ratio holds "
                        ">= 0.5 (per-tenant series from the ledger "
                        "gauge)",
            gauge="kftpu_tenant_goodput_ratio", group_by="tenant",
            min_value=0.5, slo=0.90, windows=windows),
    ]
    if goodput is not None:
        objs.append(Objective(
            name="goodput-interruptions",
            description="interruption-free fleet time: no "
                        "preemption/migration/restart lands in 90% of "
                        "intervals",
            value_fn=interruption_delta_source(goodput),
            max_value=0.0, slo=0.90, page_burn=3.0, warn_burn=1.5,
            windows=windows))
    return objs


def soak_objectives(accountant=None, *,
                    watch_lag_threshold_s: float = 0.5,
                    windows: Windows = TICK_WINDOWS) -> List[Objective]:
    """The tick-scaled objective set the seeded chaos soaks evaluate
    once per round — the CI ``slo-smoke`` contract: a clean soak fires
    NOTHING; injected watch lag pages ``watch-delivery-lag`` and a
    preemption burst pages ``goodput-interruptions``, each exactly
    once (hysteresis holds the state through the fault window).

    The watch-lag SLI is inherently WALL-CLOCK (write→drain time), so
    its threshold needs headroom against host stalls on loaded CI
    machines: 0.5s sits ~5000x above an in-process drain and 2x under
    the 1.0s lag the fault soak injects — a sub-half-second scheduler
    stall cannot fail the clean soak's zero-alert gate, the injected
    lag still pages decisively."""
    objs = [
        Objective(
            name="watch-delivery-lag",
            description="90% of watch events drain within "
                        f"{watch_lag_threshold_s}s",
            metric="kftpu_watch_delivery_lag_seconds",
            threshold_s=watch_lag_threshold_s, slo=0.90,
            page_burn=5.0, warn_burn=2.0, windows=windows,
            clear_after=2),
    ]
    if accountant is not None:
        objs.append(Objective(
            name="goodput-interruptions",
            description="no interruption lands in 90% of soak rounds",
            value_fn=interruption_delta_source(accountant),
            # A soak is short: ONE preemption burst inside the fast
            # windows must already page (burn of a single bad round
            # over the 6-tick fast_long window is 1/6/0.1 ≈ 1.67).
            max_value=0.0, slo=0.90, page_burn=1.5, warn_burn=1.0,
            windows=windows, clear_after=2))
    return objs
