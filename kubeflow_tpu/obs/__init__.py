"""Observability subsystems that sit ABOVE the span/metric primitives:
`utils/tracing.py` and `utils/monitoring.py` record what happened;
modules here turn those streams into operator-facing accounts — the
fleet goodput ledger (ISSUE 10), and the detect-and-explain layer on
top of it (ISSUE 15): the SLO engine with burn-rate alerting
(`obs/slo.py`) and the crash-dump flight recorder (`obs/flight.py`)."""

from kubeflow_tpu.obs.flight import FlightRecorder, flight_paths, stitch
from kubeflow_tpu.obs.remediate import (
    ACTIONS_JOURNAL,
    Playbook,
    RemediationController,
    remediation_objective,
)
from kubeflow_tpu.obs.goodput import (
    CATEGORIES,
    GoodputAccountant,
    chaos_policy_parity_report,
    goodput_rows_digest,
)
from kubeflow_tpu.obs.slo import (
    ALERTS_JOURNAL,
    DEFAULT_WINDOWS,
    TICK_WINDOWS,
    Objective,
    SLOEngine,
    Windows,
    default_objectives,
    soak_objectives,
)

__all__ = [
    "ACTIONS_JOURNAL",
    "ALERTS_JOURNAL",
    "CATEGORIES",
    "DEFAULT_WINDOWS",
    "FlightRecorder",
    "GoodputAccountant",
    "Objective",
    "Playbook",
    "RemediationController",
    "SLOEngine",
    "TICK_WINDOWS",
    "Windows",
    "chaos_policy_parity_report",
    "default_objectives",
    "flight_paths",
    "goodput_rows_digest",
    "remediation_objective",
    "soak_objectives",
    "stitch",
]
