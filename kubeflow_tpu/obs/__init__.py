"""Observability subsystems that sit ABOVE the span/metric primitives:
`utils/tracing.py` and `utils/monitoring.py` record what happened;
modules here turn those streams into operator-facing accounts (the
fleet goodput ledger first — ISSUE 10)."""

from kubeflow_tpu.obs.goodput import (
    CATEGORIES,
    GoodputAccountant,
    chaos_policy_parity_report,
    goodput_rows_digest,
)

__all__ = [
    "CATEGORIES",
    "GoodputAccountant",
    "chaos_policy_parity_report",
    "goodput_rows_digest",
]
