"""Observability subsystems that sit ABOVE the span/metric primitives:
`utils/tracing.py` and `utils/monitoring.py` record what happened;
modules here turn those streams into operator-facing accounts — the
fleet goodput ledger (ISSUE 10), and the detect-and-explain layer on
top of it (ISSUE 15): the SLO engine with burn-rate alerting
(`obs/slo.py`), the crash-dump flight recorder (`obs/flight.py`), and
the data-plane step profiler (`obs/profiler.py`, ISSUE 19)."""

from kubeflow_tpu.obs.flight import FlightRecorder, flight_paths, stitch
from kubeflow_tpu.obs.profiler import (
    NULL_STEP,
    SERVING_PHASES,
    TRAIN_PHASES,
    Profiler,
    TickClock,
    perfetto_json,
    perfetto_track_counts,
    profile_gate_failures,
    seeded_serving_profile,
    seeded_train_profile,
    serving_cost_catalog,
    train_cost_catalog,
)
from kubeflow_tpu.obs.remediate import (
    ACTIONS_JOURNAL,
    Playbook,
    RemediationController,
    remediation_objective,
)
from kubeflow_tpu.obs.goodput import (
    CATEGORIES,
    GoodputAccountant,
    chaos_policy_parity_report,
    goodput_rows_digest,
)
from kubeflow_tpu.obs.slo import (
    ALERTS_JOURNAL,
    DEFAULT_WINDOWS,
    TICK_WINDOWS,
    Objective,
    SLOEngine,
    Windows,
    default_objectives,
    soak_objectives,
)

__all__ = [
    "ACTIONS_JOURNAL",
    "ALERTS_JOURNAL",
    "CATEGORIES",
    "DEFAULT_WINDOWS",
    "FlightRecorder",
    "GoodputAccountant",
    "NULL_STEP",
    "Objective",
    "Playbook",
    "Profiler",
    "RemediationController",
    "SERVING_PHASES",
    "SLOEngine",
    "TICK_WINDOWS",
    "TRAIN_PHASES",
    "TickClock",
    "Windows",
    "chaos_policy_parity_report",
    "default_objectives",
    "flight_paths",
    "goodput_rows_digest",
    "perfetto_json",
    "perfetto_track_counts",
    "profile_gate_failures",
    "remediation_objective",
    "seeded_serving_profile",
    "seeded_train_profile",
    "serving_cost_catalog",
    "soak_objectives",
    "stitch",
    "train_cost_catalog",
]
