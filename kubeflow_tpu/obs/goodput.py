"""Fleet goodput ledger: slice-second attribution with conservation-gated
accounting (ISSUE 10).

Of every slice-second the hardware offered, how many were productive and
where did the rest go? The :class:`GoodputAccountant` watches the same
event stream controllers do (``api.watch()`` — TpuJob phase transitions,
``status.slice_assignment`` assign/clear, preemption/defrag/checkpoint
events) and decomposes every tracked slice's timeline into exclusive,
exhaustive categories:

- ``productive`` — held by a gang whose workers are all Running (outside
  checkpoint-save windows);
- ``queue_wait`` — free while at least one gang queues (Admitted=False /
  unplaced): capacity the scheduler could not hand to waiting demand;
- ``restart_rollback`` — held by a gang between an interruption and full
  resume (preempt → re-place → resume, spin-up included), PLUS the
  productive seconds re-done after the restart (work since the last
  checkpoint save is moved productive → restart_rollback when the
  interruption lands — recompute is rollback, not goodput);
- ``migration`` — the same window when the interruption was a defrag
  migration (the ``DefragMigration`` event names the cause BEFORE the
  eviction's status bump arrives);
- ``checkpoint_overhead`` — held by a Running gang inside a declared
  checkpoint-save window;
- ``idle_free`` — free with no queued demand.

**Conservation invariant** (the hard gate, never approximate): per slice
and per fleet, attributed time sums EXACTLY to tracked capacity-time.
All arithmetic is integer — logical ticks in the benches/soaks,
``time.monotonic_ns()`` in live runs — so the invariant is bit-exact and
a bookkeeping bug trips the gate instead of rounding away. CI gates are
tick/count-based, never wall-clock.

Chaos-vs-policy parity: both a chaos slice preemption and a scheduler
priority eviction reach the job as the SAME transition (the PR-8 seam —
``scheduler.preempt.preempt_gang`` marks the pods, the controller bumps
``status.preemptions``), and the accountant classifies off that bump, so
injected and policy preemptions attribute identically by construction
(:func:`chaos_policy_parity_report` proves it on twin worlds).

Rebuild contract: every attribution is journaled (fsync'd jsonl, the WAL
discipline) and :meth:`replay_from` re-applies the records through the
same code path the live ledger used — a SIGKILLed shard's accountant
comes back byte-identical (``fingerprint()`` equality, gated by the CI
``shard-smoke`` stage). Per-shard accountants' :meth:`rows` union like
``state_fingerprint()`` rows: globally-unique unit ids, order-independent
digest (:func:`goodput_rows_digest`).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.journal import JsonlJournal
from kubeflow_tpu.utils.monitoring import MetricsRegistry

log = get_logger("goodput")

#: The exclusive, exhaustive attribution categories (docs/observability.md).
CATEGORIES = (
    "productive",
    "queue_wait",
    "restart_rollback",
    "migration",
    "checkpoint_overhead",
    "idle_free",
)

#: Phases during which a gang holds (synthetic) capacity when the
#: scheduler does not pin concrete units.
ASSIGNED_PHASES = ("Scheduling", "Starting", "Running", "Restarting",
                   "Resizing")
TERMINAL_PHASES = ("Succeeded", "Failed")

GOODPUT_JOURNAL = "goodput.jsonl"
GOODPUT_STATE = "goodput.json"

#: Journal rollover threshold (ISSUE 15 satellite): past this byte size
#: the journal moves to ``<path>.1`` (the single-generation
#: ``Tracer.rotate_jsonl`` discipline from PR 10) and the fresh
#: generation opens with a compacting ``state`` record — so the CURRENT
#: file is always self-contained and replay stays byte-identical even
#: after the ``.1`` generation is itself replaced.
JOURNAL_ROTATE_BYTES = 4 << 20


def goodput_rows_digest(rows: Iterable[Tuple]) -> str:
    """Order-independent sha256 over ledger rows — per-shard accountants'
    rows union exactly like ``state_fingerprint()`` rows (unit ids are
    globally unique, so the union digest is layout-independent)."""
    joined = sorted("|".join(str(c) for c in r) for r in rows)
    return hashlib.sha256("\n".join(joined).encode()).hexdigest()


# The shared fsync'd-jsonl discipline (utils/journal.py since PR 16;
# the `_Journal` name stays importable — obs/slo.py and the tests bind
# it from here).
_Journal = JsonlJournal


class _JobTrack:
    """The accountant's view of one TpuJob, built from watch events."""

    __slots__ = (
        "uid", "name", "namespace", "slice_type", "num_slices",
        "alloc_slices", "phase", "admitted", "assignment", "preemptions",
        "restarts", "resizes", "interruption", "checkpointing", "deleted",
    )

    def __init__(self, uid: str, name: str, namespace: str,
                 slice_type: str, num_slices: int):
        self.uid = uid
        self.name = name
        self.namespace = namespace
        self.slice_type = slice_type
        self.num_slices = num_slices      # spec width (the desired gang)
        self.alloc_slices = num_slices    # current width (elastic resize)
        self.phase = ""
        self.admitted = True
        self.assignment = ""
        self.preemptions = 0
        self.restarts = 0
        self.resizes = 0
        self.interruption: Optional[str] = None  # "preempt"|"migration"|...
        self.checkpointing = False
        self.deleted = False

    @property
    def live(self) -> bool:
        return not self.deleted and self.phase not in TERMINAL_PHASES


class GoodputAccountant:
    """Per-slice goodput ledger over a fixed unit set.

    Time is an opaque monotone integer; ``tick_seconds`` scales it to
    seconds for reporting only (1.0 for logical-tick drivers, 1e-9 for
    ``time.monotonic_ns()`` live runs). All ledger arithmetic stays in
    integers so conservation is exact, never approximate.
    """

    def __init__(
        self,
        units: Dict[str, List[str]],      # slice_type -> ordered unit uids
        *,
        tick_seconds: float = 1.0,
        registry: Optional[MetricsRegistry] = None,
        journal_path: str = "",
        fsync: bool = True,
        rotate_bytes: int = JOURNAL_ROTATE_BYTES,
        explicit_assignments: bool = False,
        track_rollback: bool = True,
        # Tenant tree (ISSUE 13): when set, every job is attributed to
        # its tenant path (namespace resolved through the tree) via a
        # VERSIONED journal record ({"op": "tn", "v": 2, ...}) — old
        # journals carry no such records and replay byte-identically.
        tenants=None,
    ):
        self._lock = threading.RLock()
        self.tick_seconds = float(tick_seconds)
        # ``explicit_assignments``: a GangScheduler fleet pins concrete
        # unit uids into status.slice_assignment — the accountant then
        # NEVER synthesizes an allocation (a preempted gang holds
        # nothing until re-placed). Without a scheduler, gangs hold
        # shape-only assignments and the accountant allocates sticky
        # synthetic units per phase.
        self.explicit_assignments = explicit_assignments
        # ``track_rollback=False`` models continuous checkpointing (the
        # sleep-free sims where finished work is never lost): no
        # productive second is ever reclassified on an interruption.
        self.track_rollback = track_rollback
        self._order: Dict[str, List[str]] = {
            st: list(us) for st, us in sorted(units.items())
        }
        self._unit_type: Dict[str, str] = {}
        for st, us in self._order.items():
            for u in us:
                if u in self._unit_type:
                    raise ValueError(f"duplicate goodput unit {u!r}")
                self._unit_type[u] = st
        # The ledger proper: integer category tallies per unit, plus an
        # INDEPENDENTLY accumulated tracked total — conservation compares
        # the two, so a missed or double attribution trips the gate
        # instead of vanishing into a derived sum.
        self._cats: Dict[str, Dict[str, int]] = {
            u: {} for u in self._unit_type
        }
        self._tracked: Dict[str, int] = {u: 0 for u in self._unit_type}
        self._active: Set[str] = set(self._unit_type)
        # Per-job ledger (uid-keyed; queue_wait here is demand-side:
        # seconds x requested slices while the gang waited).
        self._job_cats: Dict[str, Dict[str, int]] = {}
        self._job_meta: Dict[str, Tuple[str, str]] = {}
        self._unsaved: Dict[str, int] = {}
        # Elastic resize bookkeeping (ISSUE 11): per-job resize count and
        # the counterfactual ledger — productive slice-ticks earned while
        # the gang ran UNDER its spec width. A restart-only twin would
        # have spent exactly those ticks queued for full capacity, so
        # this is the "slice-seconds saved vs the restart counterfactual"
        # surface tpuctl shows (docs/elastic.md).
        self._job_resizes: Dict[str, int] = {}
        self._job_degraded: Dict[str, int] = {}
        # Tenant attribution (ISSUE 13): uid -> tenant path, journaled
        # as versioned "tn" records so replay rebuilds the rollup.
        self.tenants = tenants
        self._job_tenant: Dict[str, str] = {}
        self.interruptions: Dict[str, int] = {
            "preempt": 0, "migration": 0, "restart": 0, "resize": 0,
        }
        # Event-stream state.
        self._jobs: Dict[str, _JobTrack] = {}
        self._pending_migration: Set[str] = set()
        self._alloc: Dict[str, List[str]] = {}
        self._unit_job: Dict[str, str] = {}
        self._last = 0
        self._api = None
        self._queue = None
        self._journal = _Journal(journal_path, fsync)
        self._rotate_bytes = int(rotate_bytes)
        self._replaying = False
        self.metrics_seconds = None
        self.metrics_ratio = None
        self.metrics_tenant_ratio = None
        self.metrics_tenant_fair = None
        if registry is not None:
            self.metrics_seconds = registry.counter(
                "kftpu_goodput_slice_seconds_total",
                "Attributed slice-seconds by goodput category",
                labels=("category",),
            )
            self.metrics_ratio = registry.gauge(
                "kftpu_job_goodput_ratio",
                "Productive fraction of each job's attributed "
                "slice-seconds",
                labels=("namespace", "name"),
            )
            self.metrics_tenant_ratio = registry.gauge(
                "kftpu_tenant_goodput_ratio",
                "Productive fraction of each tenant subtree's "
                "attributed slice-seconds (ledger rollup)",
                labels=("tenant",),
            )
            self.metrics_tenant_fair = registry.gauge(
                "kftpu_tenant_fair_share",
                "Each active tenant's weighted fair fraction of the "
                "fleet (hierarchical split by Profile weight)",
                labels=("tenant",),
            )

    # ----------------- construction -----------------

    @classmethod
    def from_capacity(cls, capacity: Dict[str, int], *,
                      unit_prefix: str = "", **kw) -> "GoodputAccountant":
        """Synthetic units out of the admission ledger's vocabulary
        (slice_type -> count). ``unit_prefix`` namespaces the unit ids so
        per-shard accountants' rows stay globally unique and union like
        ``state_fingerprint()`` rows."""
        units = {
            st: [f"{unit_prefix}{st}/s{i:03d}" for i in range(int(n))]
            for st, n in sorted(capacity.items())
        }
        return cls(units, **kw)

    @classmethod
    def from_fleet(cls, fleet, **kw) -> "GoodputAccountant":
        """Track a GangScheduler fleet's REAL unit uids; assignments then
        come verbatim from ``status.slice_assignment``."""
        units: Dict[str, List[str]] = {}
        for pool in fleet.pools:
            for u in pool.units:
                units.setdefault(u.slice_type, []).append(u.uid)
        kw.setdefault("explicit_assignments", True)
        return cls(units, **kw)

    # ----------------- event stream -----------------

    def attach(self, api) -> "GoodputAccountant":
        """Subscribe to the SAME watch stream controllers consume. One
        kind=None subscription (not one queue per kind): commit order
        across kinds is what lets a DefragMigration event name the cause
        of the preemption bump that follows it."""
        self._api = api
        self._queue = api.watch(None)
        return self

    def detach(self) -> None:
        if self._api is not None and self._queue is not None:
            try:
                self._api.stop_watch(self._queue)
            except AttributeError:
                pass
            self._queue = None

    def pump(self) -> int:
        """Drain and apply every pending watch event (non-blocking)."""
        if self._queue is None:
            return 0
        import queue as _queue

        n = 0
        while True:
            try:
                ev = self._queue.get_nowait()
            except _queue.Empty:
                return n
            self.apply_event(ev)
            n += 1

    def apply_event(self, ev) -> None:
        obj = getattr(ev, "object", None)
        if obj is None:             # BOOKMARK / RELIST sentinels
            return
        kind = getattr(obj, "kind", "")
        with self._lock:
            if kind == "TpuJob":
                self._apply_job(ev.type, obj)
            elif kind == "Event":
                self._apply_platform_event(obj)

    def _apply_job(self, ev_type: str, job) -> None:
        uid = job.metadata.uid
        if ev_type == "DELETED":
            j = self._jobs.get(uid)
            if j is not None:
                j.deleted = True
            return
        j = self._jobs.get(uid)
        if j is None:
            j = self._jobs[uid] = _JobTrack(
                uid, job.metadata.name, job.metadata.namespace,
                job.spec.slice_type, job.spec.num_slices,
            )
            # Baseline the restart counters at first sight: an accountant
            # attached to an already-replayed store (restart path) must
            # not read history as fresh interruptions.
            j.preemptions = job.status.preemptions
            j.restarts = job.status.restarts
            j.resizes = job.status.resizes
            self._job_meta[uid] = (job.metadata.namespace,
                                   job.metadata.name)
            self._resolve_tenant(uid, job.metadata.namespace)
        j.slice_type = job.spec.slice_type
        j.num_slices = job.spec.num_slices
        # Elastic gangs hold capacity at their CURRENT width, not the
        # spec width (the synthetic-allocation path sizes off this).
        prev_width = j.alloc_slices
        j.alloc_slices = job.status.current_slices or job.spec.num_slices
        j.phase = job.status.phase or ""
        j.assignment = job.status.slice_assignment or ""
        j.admitted = True
        for c in job.status.conditions:
            if c.type == "Admitted":
                j.admitted = c.status != "False"
        if job.status.preemptions > j.preemptions:
            cause = ("migration" if uid in self._pending_migration
                     else "preempt")
            self._pending_migration.discard(uid)
            self._begin_interruption(j, cause)
        if job.status.restarts > j.restarts:
            self._begin_interruption(j, "restart")
        if job.status.resizes > j.resizes:
            # Elastic resize (ISSUE 11). A SHRINK resumes from the last
            # save: ONLY the recompute moves (productive-since-save ->
            # restart_rollback) — no interruption window opens, the
            # gang never left the hardware it keeps. A GROW costs
            # nothing at all: surviving replicas broadcast live state
            # to the joining workers (the elastic-DP rendezvous), so no
            # work is lost and the unsaved window stays open.
            if j.alloc_slices < prev_width:
                self._begin_interruption(j, "resize")
            else:
                self._begin_grow(j)
        j.preemptions = job.status.preemptions
        j.restarts = job.status.restarts
        j.resizes = job.status.resizes
        if j.phase == "Running":
            j.interruption = None

    def _apply_platform_event(self, ev) -> None:
        if getattr(ev, "involved_kind", "") != "TpuJob":
            return
        uid = None
        for j in self._jobs.values():
            if (j.namespace == ev.involved_namespace
                    and j.name == ev.involved_name and j.live):
                uid = j.uid
                break
        if uid is None:
            return
        if ev.reason == "DefragMigration":
            self._pending_migration.add(uid)
        elif ev.reason == "CheckpointSaved":
            self.checkpoint_saved(uid)

    # ----------------- tenant attribution (ISSUE 13) -----------------

    def _resolve_tenant(self, uid: str, namespace: str) -> None:
        """Attribute a job to its tenant path through the tree; a
        non-empty resolution is journaled as a VERSIONED record ("tn",
        v=2) so replay rebuilds the rollup. A RE-parented Profile
        re-resolves to its new path (journaled again, last record
        wins), so the job's whole ledger moves with the org chart —
        never a split where usage sits under the old path while fair
        fractions follow the new tree. Pre-ISSUE-13 journals hold no
        such records and replay byte-identically (the regression
        test's contract)."""
        if self.tenants is None:
            return
        path = self.tenants.resolve(namespace)
        if not path or self._job_tenant.get(uid) == path:
            return
        rec = {"op": "tn", "v": 2, "job": uid, "tenant": path}
        self._journal_rec(rec)
        self._apply_tn(rec)

    def set_tenants(self, tenants) -> None:
        """(Re)attach the tenant tree — the platform rebuilds it from
        Profiles each reconcile; already-known jobs resolve now."""
        with self._lock:
            self.tenants = tenants
            if tenants is None:
                return
            for uid, (ns, _name) in sorted(self._job_meta.items()):
                self._resolve_tenant(uid, ns)

    def _apply_tn(self, rec: dict) -> None:
        self._job_tenant[rec["job"]] = rec["tenant"]

    # ----------------- explicit driver hooks -----------------

    def checkpoint_saved(self, uid: str) -> None:
        """A checkpoint covering all productive work so far was durably
        saved: work before this point can no longer be lost to rollback."""
        with self._lock:
            rec = {"op": "ckpt", "job": uid}
            self._journal_rec(rec)
            self._apply_ckpt(rec)

    def set_checkpointing(self, uid: str, saving: bool) -> None:
        """Mark a Running gang as inside a checkpoint-save window — its
        slice-time attributes to ``checkpoint_overhead`` until cleared.
        (Classification input only: the per-tick journal records the
        resulting categories, so this flag itself needs no record.)"""
        with self._lock:
            j = self._jobs.get(uid)
            if j is not None:
                j.checkpointing = saving

    def set_capacity(self, capacity: Dict[str, int]) -> None:
        """Reflect offered-capacity changes (chaos reclaim / restore):
        the first N units of each type stay tracked, the rest stop
        accumulating — hardware that is not offered has no slice-seconds
        to attribute."""
        with self._lock:
            resolved = {}
            for st, n in sorted(capacity.items()):
                if st in self._order:
                    resolved[st] = max(0, min(int(n), len(self._order[st])))
            active = set(self._active)
            for st, n in resolved.items():
                order = self._order[st]
                active -= set(order)
                active |= set(order[:n])
            if active == self._active:
                return
            rec = {"op": "cap", "c": resolved}
            self._journal_rec(rec)
            self._apply_cap(rec)

    # ----------------- interruption / rollback -----------------

    def _begin_interruption(self, j: _JobTrack, cause: str) -> None:
        if cause != "resize":
            # A resize opens NO interruption window: the gang keeps its
            # surviving units and the brief Resizing republish (if any)
            # classifies through the phase, not through this flag. Only
            # the recompute moves below apply.
            j.interruption = cause
        j.checkpointing = False
        moves: Dict[str, List] = {}
        unsaved = self._unsaved.get(j.uid, 0)
        units = self._alloc.get(j.uid, [])
        target = "migration" if cause == "migration" else "restart_rollback"
        if self.track_rollback and unsaved > 0 and units:
            # Recompute-from-checkpoint: the productive seconds since the
            # last save will be re-done — move them to the interruption's
            # category, split evenly over the units that earned them
            # (clamped so a unit can never go negative: conservation is
            # a MOVE, amounts included in the journal record verbatim).
            q, r = divmod(unsaved, len(units))
            for i, u in enumerate(units):
                share = q + (1 if i < r else 0)
                share = min(share, self._cats[u].get("productive", 0))
                if share > 0:
                    moves[u] = ["productive", target, share]
        rec = {"op": "int", "job": j.uid, "cause": cause, "moves": moves}
        self._journal_rec(rec)
        self._apply_int(rec)

    def _begin_grow(self, j: _JobTrack) -> None:
        """A grow-resize: tallied like every resize, but it moves no
        time and leaves the unsaved window open (live-state broadcast,
        nothing to recompute)."""
        rec = {"op": "int", "job": j.uid, "cause": "resize",
               "moves": {}, "grow": 1}
        self._journal_rec(rec)
        self._apply_int(rec)

    # ----------------- the tick -----------------

    def tick(self, now: int) -> None:
        """Attribute the interval since the previous tick: every tracked
        unit's elapsed time lands in exactly one category (the state as
        classified NOW, after :meth:`pump` applied pending events)."""
        with self._lock:
            now = int(now)
            dt = now - self._last
            if dt <= 0:
                return
            states = self._classify()
            queued = self._queued_demand()
            # Degraded-productive (the elastic counterfactual): units
            # productive for a gang currently running BELOW its spec
            # width. Computed here — not at apply time — so journal
            # replay rebuilds it without needing the event stream.
            degraded: Dict[str, int] = {}
            for u, (cat, uid) in states.items():
                if cat != "productive" or not uid:
                    continue
                j = self._jobs.get(uid)
                if j is not None and \
                        len(self._alloc.get(uid, [])) < j.num_slices:
                    degraded[uid] = degraded.get(uid, 0) + 1
            rec = {
                "op": "tick", "t": now, "dt": dt,
                "s": {u: [cat, job] for u, (cat, job) in states.items()},
                "q": queued,
            }
            if degraded:
                rec["dg"] = degraded
            self._journal_rec(rec)
            self._apply_tick(rec)

    def _classify(self) -> Dict[str, Tuple[str, str]]:
        """{unit: (category, job_uid or "")} over the active units."""
        self._refresh_allocations()
        # Queued demand PER SLICE TYPE: a free v5e-16 cannot serve a
        # queued v4-8 gang, so cross-type demand must not relabel it
        # queue_wait — that would read a type-mismatched idle fleet as
        # demand-starved.
        demand_by_type: Dict[str, int] = {}
        for uid, n in self._queued_demand().items():
            j = self._jobs.get(uid)
            if j is not None:
                demand_by_type[j.slice_type] = (
                    demand_by_type.get(j.slice_type, 0) + n)
        out: Dict[str, Tuple[str, str]] = {}
        free_by_type: Dict[str, List[str]] = {}
        for st in self._order:
            for u in self._order[st]:
                if u not in self._active:
                    continue
                uid = self._unit_job.get(u)
                j = self._jobs.get(uid) if uid else None
                if j is not None:
                    if j.checkpointing and j.phase == "Running":
                        cat = "checkpoint_overhead"
                    elif j.phase == "Running":
                        cat = "productive"
                    elif j.interruption == "migration":
                        cat = "migration"
                    else:
                        cat = "restart_rollback"
                    out[u] = (cat, uid)
                else:
                    free_by_type.setdefault(st, []).append(u)
        # Supply-side queue_wait: free capacity while SAME-TYPE demand
        # queues. The lowest-ordered free units absorb it; the rest are
        # genuinely idle.
        for st, frees in free_by_type.items():
            demand = demand_by_type.get(st, 0)
            for i, u in enumerate(frees):
                out[u] = ("queue_wait" if i < demand else "idle_free", "")
        return out

    def _queued_demand(self) -> Dict[str, int]:
        """{job_uid: num_slices} for gangs waiting without capacity —
        Admitted=False, or parked un-placed (phase Pending/empty)."""
        out: Dict[str, int] = {}
        for uid, j in self._jobs.items():
            if not j.live or self._alloc.get(uid):
                continue
            if not j.admitted or j.phase in ("", "Pending"):
                out[uid] = j.num_slices
        return out

    def _refresh_allocations(self) -> None:
        from kubeflow_tpu.scheduler.placement import parse_assignment

        for uid, j in sorted(
                self._jobs.items(),
                key=lambda kv: (kv[1].namespace, kv[1].name, kv[0])):
            desired: List[str] = []
            if j.live:
                explicit = parse_assignment(j.assignment)
                if explicit:
                    desired = [u for u in explicit if u in self._unit_type]
                elif (not self.explicit_assignments
                      and j.phase in ASSIGNED_PHASES):
                    # Sticky synthetic allocation: the lowest free units
                    # of the job's type, kept until the gang lets go.
                    # Sized at the CURRENT width (elastic resizes shrink
                    # or grow it; fixed gangs: alloc == spec).
                    held = self._alloc.get(uid, [])
                    if len(held) == j.alloc_slices and all(
                            self._unit_type.get(u) == j.slice_type
                            for u in held):
                        desired = held
                    else:
                        desired = list(held)
                        free = [
                            u for u in self._order.get(j.slice_type, [])
                            if self._unit_job.get(u) in (None, uid)
                            and u not in desired
                        ]
                        while len(desired) < j.alloc_slices and free:
                            desired.append(free.pop(0))
                        desired = desired[:j.alloc_slices]
            self._set_alloc(uid, desired)
        # Jobs gone from the table entirely keep nothing.
        for uid in list(self._alloc):
            if uid not in self._jobs:
                self._set_alloc(uid, [])

    def _set_alloc(self, uid: str, units: List[str]) -> None:
        for u in self._alloc.get(uid, []):
            if self._unit_job.get(u) == uid:
                del self._unit_job[u]
        if units:
            self._alloc[uid] = list(units)
            for u in units:
                self._unit_job[u] = uid
        else:
            self._alloc.pop(uid, None)

    # ----------------- record application (live AND replay) -----------------

    def _journal_rec(self, rec: dict) -> None:
        if self._replaying:
            return
        # Rotation check BEFORE appending: every record journaled so
        # far has been applied (journal-then-apply per record), so the
        # compacting state head written into the fresh generation
        # covers the rotated-out file EXACTLY — the current file then
        # replays alone even after .1 is replaced by the next rollover.
        if rec.get("op") != "state" \
                and self._journal.maybe_rotate(self._rotate_bytes):
            self._journal.append({"op": "state", "t": self._last,
                                  "state": self.dump_state()})
        self._journal.append(rec)

    def _apply_record(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "tick":
            self._apply_tick(rec)
        elif op == "int":
            self._apply_int(rec)
        elif op == "ckpt":
            self._apply_ckpt(rec)
        elif op == "cap":
            self._apply_cap(rec)
        elif op == "tn":
            # v2 record (ISSUE 13): tenant attribution. Unknown future
            # versions of this record would land here too — applied by
            # shape, never by guessing.
            self._apply_tn(rec)
        elif op == "state":
            # A compacted journal's head: the full ledger state at
            # compaction time (see replay_from).
            self.load_state(rec["state"])
            self._last = int(rec["t"])

    def _apply_tick(self, rec: dict) -> None:
        dt = int(rec["dt"])
        cat_totals: Dict[str, int] = {}
        for u, (cat, uid) in rec["s"].items():
            cats = self._cats.get(u)
            if cats is None:
                continue
            cats[cat] = cats.get(cat, 0) + dt
            self._tracked[u] = self._tracked.get(u, 0) + dt
            cat_totals[cat] = cat_totals.get(cat, 0) + dt
            if uid:
                jc = self._job_cats.setdefault(uid, {})
                jc[cat] = jc.get(cat, 0) + dt
                if cat == "productive":
                    self._unsaved[uid] = self._unsaved.get(uid, 0) + dt
        for uid, n in rec.get("q", {}).items():
            jc = self._job_cats.setdefault(uid, {})
            jc["queue_wait"] = jc.get("queue_wait", 0) + dt * int(n)
        for uid, n in rec.get("dg", {}).items():
            self._job_degraded[uid] = (
                self._job_degraded.get(uid, 0) + dt * int(n))
        self._last = int(rec["t"])
        if self.metrics_seconds is not None:
            for cat, n in sorted(cat_totals.items()):
                self.metrics_seconds.inc(n * self.tick_seconds,
                                         category=cat)
        if self.metrics_ratio is not None:
            for uid, jc in self._job_cats.items():
                meta = self._job_meta.get(uid)
                total = sum(jc.values())
                if meta is not None and total > 0:
                    self.metrics_ratio.set(
                        jc.get("productive", 0) / total,
                        namespace=meta[0], name=meta[1])
        if self.metrics_tenant_ratio is not None and self._job_tenant:
            leaf_cats = self._tenant_leaf_cats_locked(self._job_tenant)
            for path, cats in sorted(leaf_cats.items()):
                total = sum(cats.values())
                if total > 0:
                    self.metrics_tenant_ratio.set(
                        cats.get("productive", 0) / total, tenant=path)
            if self.tenants is not None:
                active = {p.rsplit("/", 1)[-1] for p in leaf_cats}
                for name, f in sorted(
                        self.tenants.fair_fractions(active).items()):
                    self.metrics_tenant_fair.set(
                        f, tenant=self.tenants.resolve(name) or name)

    def _apply_int(self, rec: dict) -> None:
        cause = rec["cause"]
        self.interruptions[cause] = self.interruptions.get(cause, 0) + 1
        uid = rec["job"]
        if cause == "resize":
            self._job_resizes[uid] = self._job_resizes.get(uid, 0) + 1
        moved_total = 0
        target = None
        for u, (frm, to, amount) in rec.get("moves", {}).items():
            amount = int(amount)
            cats = self._cats.get(u)
            if cats is None:
                continue
            cats[frm] = cats.get(frm, 0) - amount
            cats[to] = cats.get(to, 0) + amount
            moved_total += amount
            target = to
        if moved_total and target is not None:
            jc = self._job_cats.setdefault(uid, {})
            jc["productive"] = jc.get("productive", 0) - moved_total
            jc[target] = jc.get(target, 0) + moved_total
        if not rec.get("grow"):
            # Grows lose nothing: the unsaved window stays open for the
            # next real interruption to reclassify.
            self._unsaved[uid] = 0

    def _apply_ckpt(self, rec: dict) -> None:
        self._unsaved[rec["job"]] = 0

    def _apply_cap(self, rec: dict) -> None:
        for st, n in rec["c"].items():
            order = self._order.get(st, [])
            self._active -= set(order)
            self._active |= set(order[:int(n)])

    # ----------------- replay / persistence -----------------

    def replay_from(self, journal_path: str) -> int:
        """Rebuild the ledger by re-applying the journal through the SAME
        application path the live accountant used — byte-identical by
        construction. When replaying our OWN journal, the log is then
        compacted to one state record (the ledger.jsonl discipline): a
        respawn's replay cost stays bounded by ledger size, not by how
        many ticks the previous incarnations lived. Returns records
        applied. Rotated journals replay BOTH generations (``<path>.1``
        then ``<path>`` — the single-generation rollover discipline),
        and compaction removes the stale ``.1`` the state record now
        covers."""
        recs = _Journal.read_generations(journal_path)
        with self._lock:
            self._replaying = True
            try:
                for rec in recs:
                    self._apply_record(rec)
            finally:
                self._replaying = False
            if recs and journal_path == self._journal.path:
                self._journal.close()
                _Journal.compact(journal_path,
                                 {"op": "state", "t": self._last,
                                  "state": self.dump_state()})
        if recs:
            log.info("goodput journal replayed", kv={
                "records": len(recs), "last_tick": self._last,
            })
        return len(recs)

    def last_tick(self) -> int:
        return self._last

    def reset_clock(self, now: int) -> None:
        """Establish the attribution baseline WITHOUT attributing —
        process start / state-restore time is not platform time."""
        with self._lock:
            self._last = int(now)

    def close(self) -> None:
        self.detach()
        self._journal.close()

    def dump_state(self) -> dict:
        """Ledger totals as plain JSON (Platform persistence across
        tpuctl invocations — the timeline between processes is not
        platform time and is deliberately not counted)."""
        with self._lock:
            return {
                "units": {
                    u: {"cats": dict(self._cats[u]),
                        "tracked": self._tracked[u]}
                    for u in sorted(self._unit_type)
                },
                "jobs": {uid: dict(c)
                         for uid, c in sorted(self._job_cats.items())},
                "meta": {uid: list(m)
                         for uid, m in sorted(self._job_meta.items())},
                "unsaved": {uid: n for uid, n in sorted(
                    self._unsaved.items()) if n},
                "interruptions": dict(self.interruptions),
                "active": sorted(self._active),
                "tick_seconds": self.tick_seconds,
                "resizes": {uid: n for uid, n in sorted(
                    self._job_resizes.items()) if n},
                "degraded": {uid: n for uid, n in sorted(
                    self._job_degraded.items()) if n},
                **({"job_tenants": dict(sorted(self._job_tenant.items()))}
                   if self._job_tenant else {}),
            }

    def load_state(self, state: dict) -> None:
        with self._lock:
            for u, rec in state.get("units", {}).items():
                if u in self._cats:
                    self._cats[u] = {k: int(v)
                                     for k, v in rec["cats"].items()}
                    self._tracked[u] = int(rec["tracked"])
            self._job_cats = {
                uid: {k: int(v) for k, v in c.items()}
                for uid, c in state.get("jobs", {}).items()
            }
            for uid, m in state.get("meta", {}).items():
                self._job_meta.setdefault(uid, (m[0], m[1]))
            self._unsaved = {uid: int(n)
                             for uid, n in state.get("unsaved", {}).items()}
            for k, v in state.get("interruptions", {}).items():
                self.interruptions[k] = int(v)
            if "active" in state:
                self._active = {u for u in state["active"]
                                if u in self._unit_type}
            self._job_resizes = {
                uid: int(n)
                for uid, n in state.get("resizes", {}).items()}
            self._job_degraded = {
                uid: int(n)
                for uid, n in state.get("degraded", {}).items()}
            for uid, path in state.get("job_tenants", {}).items():
                self._job_tenant[uid] = str(path)

    # ----------------- read surfaces -----------------

    def conservation(self) -> Dict[str, Any]:
        """The invariant, checked exactly: per unit AND per fleet, the
        category sum equals the independently-accumulated tracked total
        (ints — equality, never tolerance). Negative tallies are
        violations too (a bad move)."""
        with self._lock:
            violations = []
            for u in self._unit_type:
                cats = self._cats[u]
                if sum(cats.values()) != self._tracked[u] or any(
                        v < 0 for v in cats.values()):
                    violations.append(u)
            total_cats = sum(sum(c.values()) for c in self._cats.values())
            total_tracked = sum(self._tracked.values())
            return {
                "exact": not violations and total_cats == total_tracked,
                "violations": violations,
                "attributed": total_cats,
                "tracked": total_tracked,
            }

    def rows(self) -> List[Tuple[str, str, str, str]]:
        """The fingerprintable ledger rows; per-shard accountants' rows
        union into one fleet digest (ids are globally unique)."""
        with self._lock:
            rows: List[Tuple[str, str, str, str]] = []
            for u in sorted(self._unit_type):
                for cat, n in sorted(self._cats[u].items()):
                    rows.append(("unit", u, cat, str(n)))
                rows.append(("tracked", u, "", str(self._tracked[u])))
            for uid in sorted(self._job_cats):
                for cat, n in sorted(self._job_cats[uid].items()):
                    rows.append(("job", uid, cat, str(n)))
            for uid in sorted(self._unsaved):
                if self._unsaved[uid]:
                    rows.append(("unsaved", uid, "", str(self._unsaved[uid])))
            for uid in sorted(self._job_resizes):
                if self._job_resizes[uid]:
                    rows.append(("resizes", uid, "",
                                 str(self._job_resizes[uid])))
            for uid in sorted(self._job_degraded):
                if self._job_degraded[uid]:
                    rows.append(("degraded", uid, "",
                                 str(self._job_degraded[uid])))
            for uid in sorted(self._job_tenant):
                rows.append(("tenant", uid, "", self._job_tenant[uid]))
            for cause in sorted(self.interruptions):
                rows.append(("interruptions", cause, "",
                             str(self.interruptions[cause])))
            return rows

    def fingerprint(self) -> Tuple[Dict[str, int], str]:
        """(fleet category totals, order-independent digest) — the
        byte-identical-across-SIGKILL gate compares these."""
        with self._lock:
            totals: Dict[str, int] = {}
            for cats in self._cats.values():
                for cat, n in cats.items():
                    totals[cat] = totals.get(cat, 0) + n
        return totals, goodput_rows_digest(self.rows())

    def comparable(self) -> Dict[str, Any]:
        """Uid-independent view for A/B parity: fleet category totals,
        interruption tallies, and per-job ledgers keyed by ns/name."""
        with self._lock:
            totals: Dict[str, int] = {}
            for cats in self._cats.values():
                for cat, n in cats.items():
                    totals[cat] = totals.get(cat, 0) + n
            jobs = {}
            for uid, jc in self._job_cats.items():
                meta = self._job_meta.get(uid, ("", uid))
                jobs[f"{meta[0]}/{meta[1]}"] = dict(sorted(jc.items()))
            return {
                "categories_ticks": dict(sorted(totals.items())),
                "interruptions": dict(sorted(self.interruptions.items())),
                "jobs": dict(sorted(jobs.items())),
            }

    #: Categories during which a gang HOLDS capacity — the usage share
    #: the fair-share scoreboard compares against fair fractions
    #: (queue_wait is demand-side in the per-job ledger and idle_free
    #: belongs to nobody).
    HELD_CATEGORIES = ("productive", "restart_rollback", "migration",
                       "checkpoint_overhead")

    def _tenant_leaf_cats_locked(
            self, job_tenant: Dict[str, str]) -> Dict[str, Dict[str, int]]:
        """Leaf tenant path -> summed per-job category ticks (caller
        holds the lock)."""
        out: Dict[str, Dict[str, int]] = {}
        for uid, jc in self._job_cats.items():
            path = job_tenant.get(uid)
            if not path:
                continue
            agg = out.setdefault(path, {})
            for c, n in jc.items():
                agg[c] = agg.get(c, 0) + n
        return out

    def tenant_snapshot(self, tree=None) -> Dict[str, Any]:
        """The per-tenant scoreboard (ISSUE 13): the ledger's per-job
        rows aggregated up the tenant tree. Every node of the hierarchy
        gets the sum of its subtree's attributed slice-ticks, its usage
        SHARE (held ticks / fleet tracked ticks), its weighted FAIR
        fraction (hierarchical split among tenants with live usage or
        queued demand), the DEFICIT between the two, its goodput ratio,
        and — where the Profile declares ``goodput_slo`` — the
        error-budget burn rate and alert state. ``tree`` overrides the
        attached tree for read-only resolution (the tpuctl path, where
        the tree is rebuilt from Profiles at command time). Same ledger
        rows as :meth:`snapshot` — one source of truth."""
        from kubeflow_tpu.tenancy.drf import slo_burn, slo_state

        with self._lock:
            tree = tree if tree is not None else self.tenants
            job_tenant = dict(self._job_tenant)
            if tree is not None:
                for uid, (ns, _n) in self._job_meta.items():
                    if uid not in job_tenant:
                        path = tree.resolve(ns)
                        if path:
                            job_tenant[uid] = path
            leaf_cats = self._tenant_leaf_cats_locked(job_tenant)
            tracked = sum(self._tracked.values())
            # Roll leaf ledgers up the tree: every prefix of a leaf
            # path aggregates its subtree.
            node_cats: Dict[str, Dict[str, int]] = {}
            for path, cats in leaf_cats.items():
                parts = path.split("/")
                for i in range(len(parts)):
                    node = "/".join(parts[:i + 1])
                    agg = node_cats.setdefault(node, {})
                    for c, n in cats.items():
                        agg[c] = agg.get(c, 0) + n
            node_fair: Dict[str, float] = {}
            if tree is not None:
                active = {p.rsplit("/", 1)[-1] for p in leaf_cats}
                for name, f in tree.fair_fractions(active).items():
                    path = tree.resolve(name) or name
                    parts = path.split("/")
                    for i in range(len(parts)):
                        node = "/".join(parts[:i + 1])
                        node_fair[node] = node_fair.get(node, 0.0) + f
            ts = self.tick_seconds
            tenants: Dict[str, Dict[str, Any]] = {}
            for node in sorted(node_cats):
                cats = node_cats[node]
                total = sum(cats.values())
                held = sum(cats.get(c, 0) for c in self.HELD_CATEGORIES)
                share = held / tracked if tracked else 0.0
                fair = node_fair.get(node, 0.0)
                ratio = (cats.get("productive", 0) / total
                         if total else 0.0)
                entry: Dict[str, Any] = {
                    "categories_ticks": dict(sorted(cats.items())),
                    "slice_seconds": round(total * ts, 6),
                    "held_ticks": held,
                    "share": round(share, 6),
                    "fair_share": round(fair, 6),
                    "deficit": round(fair - share, 6),
                    "goodput_ratio": round(ratio, 6),
                    # True = jobs attribute DIRECTLY to this node (a
                    # leaf path, or an org running its own workloads);
                    # False = pure subtree rollup. Consumers computing
                    # fair fractions (tpuctl queue) must count only
                    # direct claimants — a rollup node is not one more
                    # sibling competing with its own children.
                    "direct": node in leaf_cats,
                }
                tnode = (tree.node(node.rsplit("/", 1)[-1])
                         if tree is not None else None)
                if tnode is not None:
                    entry["weight"] = tnode.weight
                    if tnode.goodput_slo > 0:
                        burn = slo_burn(ratio, tnode.goodput_slo)
                        entry["goodput_slo"] = tnode.goodput_slo
                        entry["slo_burn"] = (round(burn, 4)
                                             if burn is not None else None)
                        entry["slo_state"] = slo_state(burn)
                tenants[node] = entry
            return {
                "tracked_ticks": tracked,
                "conserved": self.conservation()["exact"],
                "tenants": tenants,
            }

    def snapshot(self) -> Dict[str, Any]:
        """The report/CLI surface: integer tick tallies (what CI gates
        on), scaled seconds, ratios, per-job drill-down."""
        with self._lock:
            cons = self.conservation()
            totals: Dict[str, int] = {c: 0 for c in CATEGORIES}
            for cats in self._cats.values():
                for cat, n in cats.items():
                    totals[cat] = totals.get(cat, 0) + n
            tracked = sum(self._tracked.values())
            ts = self.tick_seconds
            jobs: Dict[str, Dict[str, Any]] = {}
            for uid, jc in sorted(self._job_cats.items()):
                meta = self._job_meta.get(uid, ("", uid))
                total = sum(jc.values())
                entry = {
                    "categories_ticks": dict(sorted(jc.items())),
                    "categories_s": {c: round(n * ts, 6)
                                     for c, n in sorted(jc.items())},
                    "slice_seconds": round(total * ts, 6),
                    "goodput_ratio": round(
                        jc.get("productive", 0) / total, 6) if total else 0.0,
                }
                # Elastic drill-down (ISSUE 11): resize count and the
                # restart counterfactual — productive slice-time earned
                # while running under spec width, which a restart-only
                # twin would have spent queued for full capacity.
                if self._job_resizes.get(uid) or self._job_degraded.get(uid):
                    entry["resizes"] = self._job_resizes.get(uid, 0)
                    entry["degraded_productive_ticks"] = (
                        self._job_degraded.get(uid, 0))
                    entry["counterfactual_saved_s"] = round(
                        self._job_degraded.get(uid, 0) * ts, 6)
                jobs[f"{meta[0]}/{meta[1]}"] = entry
            return {
                "tick_seconds": ts,
                "units": len(self._unit_type),
                "active_units": len(self._active),
                "categories_ticks": {c: totals.get(c, 0)
                                     for c in CATEGORIES},
                "tracked_ticks": tracked,
                "categories_s": {c: round(totals.get(c, 0) * ts, 6)
                                 for c in CATEGORIES},
                "tracked_slice_seconds": round(tracked * ts, 6),
                "goodput_ratio": round(
                    totals.get("productive", 0) / tracked, 6)
                if tracked else 0.0,
                "conserved": cons["exact"],
                "interruptions": dict(sorted(self.interruptions.items())),
                # Fleet-wide elastic counterfactual (docs/elastic.md).
                "degraded_productive_ticks": sum(
                    self._job_degraded.values()),
                "counterfactual_saved_s": round(
                    sum(self._job_degraded.values()) * ts, 6),
                "jobs": jobs,
                # Tenant rollup (ISSUE 13) — present only once tenant
                # attribution exists, so pre-tenant reports keep their
                # exact shape.
                **({"tenants": self.tenant_snapshot()["tenants"]}
                   if self._job_tenant else {}),
            }


# --------------------------------------------------------------------------
# Chaos-vs-policy attribution parity
# --------------------------------------------------------------------------


def chaos_policy_parity_report(*, seed: int = 0,
                               ticks_before: int = 3,
                               ticks_after: int = 4) -> Dict[str, Any]:
    """Twin single-gang worlds, identical except for WHO evicts the
    slice: the chaos :class:`SlicePreemptor` vs the scheduler's policy
    seam (``scheduler.preempt.preempt_gang`` — the one eviction path of
    PR 8). Both accountants must produce IDENTICAL ledgers (category
    totals, interruption tallies, per-job drill-downs): injected faults
    and policy decisions may never drift apart in goodput terms."""
    from kubeflow_tpu.controlplane.api.meta import ObjectMeta
    from kubeflow_tpu.controlplane.api.types import (
        MeshAxesSpec,
        TpuJob,
        TpuJobSpec,
    )
    from kubeflow_tpu.controlplane.controllers.podrunner import FakeKubelet
    from kubeflow_tpu.controlplane.controllers.tpujob import TpuJobController
    from kubeflow_tpu.controlplane.runtime import (
        ControllerManager,
        InMemoryApiServer,
    )

    def world(evict) -> GoodputAccountant:
        registry = MetricsRegistry()
        api = InMemoryApiServer(registry=registry)
        mgr = ControllerManager(api, registry)
        mgr.register(TpuJobController(api, registry, hbm_check=False,
                                      capacity={"v5e-16": 1}))
        kubelet = FakeKubelet(api, registry, outcome=lambda name: None)
        mgr.register(kubelet)
        acc = GoodputAccountant.from_capacity({"v5e-16": 1})
        acc.attach(api)
        api.create(TpuJob(
            metadata=ObjectMeta(name="parity", namespace="obs"),
            spec=TpuJobSpec(slice_type="v5e-16", mesh=MeshAxesSpec(dp=-1),
                            backoff_seconds=0.0, max_restarts=3,
                            preemption_policy="restart"),
        ))
        tick = 0

        def step():
            nonlocal tick
            mgr.run_until_idle(max_iterations=50000,
                               include_timers_within=120.0)
            kubelet.tick()
            mgr.run_until_idle(max_iterations=50000,
                               include_timers_within=120.0)
            acc.pump()
            tick += 1
            acc.tick(tick)

        for _ in range(ticks_before):
            step()
        job = api.get("TpuJob", "parity", "obs")
        evict(api, job)
        for _ in range(ticks_after):
            step()
        mgr.close()
        acc.detach()
        return acc

    def chaos_evict(api, job):
        from kubeflow_tpu.chaos.preemptor import SlicePreemptor

        SlicePreemptor(api, seed=seed).preempt(job)

    def policy_evict(api, job):
        from kubeflow_tpu.scheduler import preempt as preempt_mod

        preempt_mod.preempt_gang(api, job)

    chaos_acc = world(chaos_evict)
    policy_acc = world(policy_evict)
    a, b = chaos_acc.comparable(), policy_acc.comparable()
    return {
        "identical": a == b,
        "conserved": (chaos_acc.conservation()["exact"]
                      and policy_acc.conservation()["exact"]),
        "preemptions_attributed": a["interruptions"].get("preempt", 0),
        "chaos": a,
        "policy": b,
    }
