"""Self-healing remediation: SLO pages drive budgeted playbooks
(ISSUE 17).

PR 15 made the platform *notice* (burn-rate pages, exemplars, flight
dumps); this module makes it *act* — and makes every action defensible:

- The :class:`RemediationController` subscribes to the
  :class:`~kubeflow_tpu.obs.slo.SLOEngine` alert FSM: after each
  ``evaluate(now)`` pass the driver hands the clock (and optionally the
  fired transitions / an external state map) to ``tick(now)``, which
  maps each PAGING objective to its registered :class:`Playbook`.
- A playbook is an *actuation seam the platform already has*, wrapped
  in guardrails: drain a sick serving backend (``lb.set_backends``),
  requeue parked gangs (the PR-8 park path's ``kick_timers``), grow an
  under-SLO elastic gang (``ElasticController.sweep`` ->
  ``try_grow``), shrink a gang via the ONE eviction seam
  (``scheduler.preempt.preempt_slice_group``), respawn a wedged shard
  (``ShardedControlPlane.kill``/``restart``). Factories for all five
  live at the bottom of this module; custom playbooks are one dataclass.
- Guardrails are the point, not the actions: a per-playbook action
  BUDGET, a COOLDOWN between actions, one outstanding action at a time,
  a fsync'd ``actions.jsonl`` journaled **before** each apply (the
  KF102/KF106 discipline; rotate-before-append with a state head,
  byte-identical :meth:`RemediationController.replay_from`,
  shard-SIGKILL-safe), FlightRecorder dumps bracketing every action
  (``remediate-pre-<playbook>`` / ``remediate-post-<playbook>``) as
  evidence, and a goodput-ledger "did it pay off" VERDICT journaled
  ``verify_after`` clock units later: paid iff the paged series cleared
  AND the ledger-measured cost stayed within the playbook's
  ``cost_budget``. A playbook whose cost goes unrepaid
  ``unpaid_disable_after`` actions in a row auto-disables itself and
  pages ``remediation-disabled`` (via the
  ``kftpu_remediation_disabled`` gauge + :func:`remediation_objective`)
  instead of flapping the fleet.

Clock discipline: like the SLOEngine, ``tick(now)`` is the one clock
input — monotone seconds on a live platform, integer rounds in seeded
soaks. No wall-clock reads here (KF101: this file is in the tick
domain). Deterministic: same alert sequence, same actions, byte
identical journal.

See docs/remediation.md for the operator-facing contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from typing import Any, Callable, Dict, List, Optional

from kubeflow_tpu.obs.goodput import JOURNAL_ROTATE_BYTES, _Journal
from kubeflow_tpu.obs.slo import Objective, TICK_WINDOWS, Windows
from kubeflow_tpu.utils.logging import get_logger

log = get_logger("remediate")

#: The action journal's filename under a state dir — next to
#: ``alerts.jsonl`` and ``goodput.jsonl``.
ACTIONS_JOURNAL = "actions.jsonl"


@dataclasses.dataclass(frozen=True)
class Playbook:
    """One objective -> action mapping plus its guardrails.

    ``action`` receives the (already-journaled) action record and
    actuates through an existing platform seam, returning a small
    detail dict for the scoreboard. ``precheck`` (optional) is a
    READ-ONLY feasibility probe run BEFORE anything is journaled — a
    playbook that cannot act right now (e.g. draining the last live
    backend) skips without burning budget. Budgets/cooldowns are in
    the driver's clock units (ticks in soaks, seconds live)."""

    name: str
    objective: str                      # base objective name it answers
    action: Callable[[dict], Optional[dict]]
    precheck: Optional[Callable[[dict], bool]] = None
    budget: int = 3                     # lifetime action cap
    cooldown: float = 2.0               # min clock between actions
    verify_after: float = 2.0           # clock until the verdict
    cost_budget: float = 0.0            # ledger cost a paid action may incur
    unpaid_disable_after: int = 3       # unpaid streak -> auto-disable

    def __post_init__(self):
        if not self.name or not self.objective:
            raise ValueError("playbook needs a name and an objective")
        if self.budget < 1:
            raise ValueError(f"playbook {self.name!r}: budget must be >= 1")
        if self.unpaid_disable_after < 1:
            raise ValueError(
                f"playbook {self.name!r}: unpaid_disable_after must be >= 1")


class _PBState:
    """Journal-observable runtime state for one playbook."""

    __slots__ = ("name", "actions", "paid", "unpaid", "streak",
                 "disabled", "disabled_source", "last_t", "last_verdict")

    def __init__(self, name: str):
        self.name = name
        self.actions = 0
        self.paid = 0
        self.unpaid = 0
        self.streak = 0              # consecutive unpaid verdicts
        self.disabled = ""           # reason; "" = armed
        self.disabled_source = ""    # "auto" | "operator"
        self.last_t: Optional[float] = None
        self.last_verdict: Optional[dict] = None


def series_base(series_key: str) -> str:
    """``sh03:backend-queue-wait[backend=b1]`` -> ``backend-queue-wait``
    — the base objective name a playbook is registered under. Shard
    prefixes (``shNN:`` from ``slo_union``) and ``group_by`` suffixes
    are routing detail, not identity."""
    key = series_key
    head, sep, rest = key.partition(":")
    if sep and head.startswith("sh") and head[2:].isdigit():
        key = rest
    return key.partition("[")[0]


def series_label(series_key: str) -> str:
    """The ``group_by`` value of a grouped series key ("" when the
    series is ungrouped) — how the drain playbook learns WHICH backend
    paged and the respawn playbook WHICH shard."""
    _, sep, rest = series_key.partition("[")
    if not sep:
        return ""
    body = rest.rstrip("]")
    return body.partition("=")[2]


class RemediationController:
    """Maps paging SLO objectives to budgeted, journaled, verified
    playbook actions. Thread-safe; starts no threads of its own.

    The journal (``actions.jsonl``) carries four ops — ``action``
    (written BEFORE the seam is touched), ``verdict``, ``disable`` /
    ``enable`` and the rotation ``state`` head — and replays through
    the same apply path the live controller used, so
    :meth:`fingerprint` is byte-identical across a SIGKILL mid-write
    (torn tails drop at the reader, exactly like the alert journal)."""

    def __init__(
        self,
        registry=None,                  # utils.monitoring.MetricsRegistry
        *,
        engine=None,                    # obs.slo.SLOEngine (optional)
        playbooks=(),
        journal_path: str = "",
        fsync: bool = True,
        rotate_bytes: int = JOURNAL_ROTATE_BYTES,
        recorder=None,                  # obs.flight.FlightRecorder
        dump_dir: str = "",
        accountant=None,                # obs.goodput.GoodputAccountant
        cost_fn: Optional[Callable[[], float]] = None,
        history_limit: int = 256,
    ):
        self.engine = engine
        self.recorder = recorder
        self.dump_dir = dump_dir
        self._accountant = accountant
        if cost_fn is not None:
            self._cost = cost_fn
        elif accountant is not None:
            self._cost = lambda: float(
                sum(accountant.interruptions.values()))
        else:
            self._cost = lambda: 0.0
        self._journal = _Journal(journal_path, fsync)
        self._rotate_bytes = int(rotate_bytes)
        self._replaying = False
        self._playbooks: Dict[str, Playbook] = {}
        self._by_objective: Dict[str, Playbook] = {}
        self._state: Dict[str, _PBState] = {}
        self._pending: List[dict] = []   # actions awaiting a verdict
        self._next_id = 1
        self._history_limit = int(history_limit)
        self._history: List[dict] = []
        self._lock = threading.RLock()
        self.metrics_actions = self.metrics_verdicts = None
        self.metrics_disabled = None
        if registry is not None:
            self.metrics_actions = registry.counter(
                "kftpu_remediation_actions_total",
                "Remediation playbook actions applied",
                labels=("playbook",),
            )
            self.metrics_verdicts = registry.counter(
                "kftpu_remediation_verdicts_total",
                "Goodput verdicts on remediation actions "
                "(did the action pay off?)",
                labels=("playbook", "verdict"),
            )
            self.metrics_disabled = registry.gauge(
                "kftpu_remediation_disabled",
                "1 when the playbook is disabled (auto or operator) — "
                "the remediation-disabled objective pages on it",
                labels=("playbook",),
            )
        for pb in playbooks:
            self.register(pb)

    # ----------------- wiring -----------------

    def register(self, pb: Playbook) -> None:
        with self._lock:
            if pb.name in self._playbooks:
                raise ValueError(f"duplicate playbook {pb.name!r}")
            other = self._by_objective.get(pb.objective)
            if other is not None:
                raise ValueError(
                    f"objective {pb.objective!r} already handled by "
                    f"playbook {other.name!r}")
            self._playbooks[pb.name] = pb
            self._by_objective[pb.objective] = pb
            self._state.setdefault(pb.name, _PBState(pb.name))
            if self.metrics_disabled is not None:
                st = self._state[pb.name]
                self.metrics_disabled.set(
                    1.0 if st.disabled else 0.0, playbook=pb.name)

    def set_journal(self, path: str, *, replay: bool = True) -> int:
        """(Re)attach the action journal once the state dir is known —
        the Platform wiring path, mirroring ``SLOEngine.set_journal``."""
        with self._lock:
            n = self.replay_from(path) if replay else 0
            self._journal.close()
            self._journal = _Journal(path, self._journal.fsync)
            return n

    # ----------------- the control loop -----------------

    def tick(self, now: float, *, fired=None,
             states: Optional[Dict[str, str]] = None,
             act: bool = True) -> List[dict]:
        """One remediation pass, called right after the SLO engine's
        ``evaluate(now)``. Settles due verdicts first (an action's
        outcome is judged before new actions are considered), then maps
        every paging series to its playbook through the guardrails.
        Returns the action records applied this tick. ``states``
        overrides the engine's series map — how the sharded soak's
        parent feeds ``slo_union`` state in; ``fired`` is accepted for
        symmetry with ``evaluate``'s return and future triggers.
        ``act=False`` settles verdicts only — the drivers' end-of-run
        flush, so every journaled action leaves with a verdict."""
        del fired  # paging STATE decides; transitions are advisory
        with self._lock:
            now = float(now)
            if states is None:
                states = self.engine.states() if self.engine else {}
            self._settle_verdicts(now, states)
            if not act:
                return []
            applied: List[dict] = []
            for series in sorted(k for k, v in states.items()
                                 if v == "page"):
                pb = self._by_objective.get(series_base(series))
                if pb is None:
                    continue
                st = self._state[pb.name]
                if st.disabled:
                    continue
                if any(p["playbook"] == pb.name for p in self._pending):
                    continue        # one outstanding action at a time
                if st.actions >= pb.budget:
                    continue        # budget exhausted: stop, don't flap
                if st.last_t is not None \
                        and now - st.last_t < pb.cooldown:
                    continue
                rec = {"op": "action", "t": round(now, 6),
                       "id": self._next_id, "playbook": pb.name,
                       "objective": series,
                       "cost0": round(self._cost(), 6)}
                if pb.precheck is not None and not pb.precheck(dict(rec)):
                    continue        # read-only probe: no budget burned
                self._dump(f"remediate-pre-{pb.name}")
                # KF102/KF106: the journal record lands (fsync'd)
                # BEFORE the seam is touched — a crash mid-action
                # replays as "attempted", never as silent mutation.
                self._journal_rec(rec)
                self._apply_action(rec)
                try:
                    detail = pb.action(dict(rec))
                except Exception as e:  # noqa: BLE001 — a playbook
                    # must never take the control loop down with it
                    detail = {"error": repr(e)}
                    log.error("remediation action failed", kv={
                        "playbook": pb.name, "err": repr(e)})
                self._dump(f"remediate-post-{pb.name}")
                self._pending.append({
                    "id": rec["id"], "playbook": pb.name,
                    "objective": series, "due": now + pb.verify_after,
                    "cost0": rec["cost0"]})
                shown = dict(rec)
                if detail:
                    shown["detail"] = detail
                self._remember(shown)
                log.warning("remediation action applied", kv={
                    "playbook": pb.name, "objective": series,
                    "action": rec["id"],
                    "budget": f"{st.actions}/{pb.budget}"})
                applied.append(shown)
            return applied

    def _settle_verdicts(self, now: float,
                         states: Dict[str, str]) -> None:
        due = [p for p in self._pending if p["due"] <= now]
        if not due:
            return
        self._pending = [p for p in self._pending if p["due"] > now]
        for p in due:
            pb = self._playbooks.get(p["playbook"])
            cleared = states.get(p["objective"], "ok") != "page"
            cost = round(self._cost() - p["cost0"], 6)
            budget = pb.cost_budget if pb is not None else 0.0
            paid = bool(cleared and cost <= budget + 1e-9)
            vrec = {"op": "verdict", "t": round(now, 6),
                    "action": p["id"], "playbook": p["playbook"],
                    "objective": p["objective"], "cleared": cleared,
                    "cost": cost, "paid": paid}
            self._journal_rec(vrec)
            self._apply_verdict(vrec)
            self._remember(vrec)
            st = self._state.get(p["playbook"])
            if (pb is not None and st is not None and not st.disabled
                    and st.streak >= pb.unpaid_disable_after):
                self._disable_locked(
                    p["playbook"], now, source="auto",
                    reason=f"cost unrepaid over {st.streak} "
                           "consecutive actions")

    # ----------------- operator overrides -----------------

    def disable(self, name: str, *, now: float = 0.0,
                reason: str = "operator override") -> None:
        """Journal + apply an operator disable (``tpuctl remediate
        --disable``). Unknown names raise — a typo must not silently
        journal a no-op."""
        with self._lock:
            if name not in self._state and name not in self._playbooks:
                raise KeyError(f"unknown playbook {name!r}")
            self._disable_locked(name, float(now), source="operator",
                                 reason=reason)

    def enable(self, name: str, *, now: float = 0.0) -> None:
        """Re-arm a disabled playbook (operator decision; also resets
        the unpaid streak — re-enabling into an instant re-disable
        would be a trap)."""
        with self._lock:
            if name not in self._state and name not in self._playbooks:
                raise KeyError(f"unknown playbook {name!r}")
            rec = {"op": "enable", "t": round(float(now), 6),
                   "playbook": name}
            self._journal_rec(rec)
            self._apply_enable(rec)
            self._remember(rec)

    def _disable_locked(self, name: str, now: float, *, source: str,
                        reason: str) -> None:
        rec = {"op": "disable", "t": round(now, 6), "playbook": name,
               "source": source, "reason": reason}
        self._journal_rec(rec)
        self._apply_disable(rec)
        self._remember(rec)
        log.error("remediation playbook disabled", kv={
            "playbook": name, "source": source, "reason": reason})

    # ----------------- journal / replay -----------------

    def _journal_rec(self, rec: dict) -> None:
        if self._replaying:
            return
        # Rotate BEFORE appending (the alert-journal discipline): the
        # state head then covers the rotated generation exactly.
        if rec.get("op") != "state" \
                and self._journal.maybe_rotate(self._rotate_bytes):
            self._journal.append({"op": "state",
                                  "playbooks": self._state_dict()})
        self._journal.append(rec)

    def _state_dict(self) -> Dict[str, dict]:
        return {
            name: {"actions": st.actions, "paid": st.paid,
                   "unpaid": st.unpaid, "streak": st.streak,
                   "disabled": st.disabled,
                   "disabled_source": st.disabled_source,
                   "t": st.last_t}
            for name, st in sorted(self._state.items())
        }

    def _st(self, name: str) -> _PBState:
        st = self._state.get(name)
        if st is None:
            # Replay of a journal mentioning a playbook this controller
            # has not (yet) registered: state still accrues — the
            # fingerprint gate must not depend on registration order.
            st = self._state[name] = _PBState(name)
        return st

    def _apply_action(self, rec: dict) -> None:
        st = self._st(rec["playbook"])
        st.actions += 1
        st.last_t = float(rec["t"])
        self._next_id = max(self._next_id, int(rec["id"]) + 1)
        if self.metrics_actions is not None:
            self.metrics_actions.inc(playbook=rec["playbook"])

    def _apply_verdict(self, rec: dict) -> None:
        st = self._st(rec["playbook"])
        if rec["paid"]:
            st.paid += 1
            st.streak = 0
        else:
            st.unpaid += 1
            st.streak += 1
        st.last_verdict = rec
        if self.metrics_verdicts is not None:
            self.metrics_verdicts.inc(
                playbook=rec["playbook"],
                verdict="paid" if rec["paid"] else "unpaid")

    def _apply_disable(self, rec: dict) -> None:
        st = self._st(rec["playbook"])
        st.disabled = rec.get("reason", "disabled")
        st.disabled_source = rec.get("source", "")
        if self.metrics_disabled is not None:
            self.metrics_disabled.set(1.0, playbook=rec["playbook"])

    def _apply_enable(self, rec: dict) -> None:
        st = self._st(rec["playbook"])
        st.disabled = ""
        st.disabled_source = ""
        st.streak = 0
        if self.metrics_disabled is not None:
            self.metrics_disabled.set(0.0, playbook=rec["playbook"])

    def _apply_state(self, rec: dict) -> None:
        for name, d in rec.get("playbooks", {}).items():
            st = self._st(name)
            st.actions = int(d.get("actions", 0))
            st.paid = int(d.get("paid", 0))
            st.unpaid = int(d.get("unpaid", 0))
            st.streak = int(d.get("streak", 0))
            st.disabled = d.get("disabled", "")
            st.disabled_source = d.get("disabled_source", "")
            st.last_t = d.get("t")

    def replay_from(self, journal_path: str) -> int:
        """Rebuild playbook state by re-applying the journal through
        the SAME apply path the live controller used — byte-identical
        :meth:`fingerprint`, the shard-SIGKILL gate. Actions whose
        verdict never landed (the process died inside the verify
        window) are re-armed at their ORIGINAL due time, so the next
        tick settles them from the journal's own clock."""
        recs = _Journal.read_generations(journal_path)
        with self._lock:
            self._replaying = True
            try:
                verdicts = {r.get("action") for r in recs
                            if r.get("op") == "verdict"}
                for rec in recs:
                    op = rec.get("op")
                    if op == "action":
                        self._apply_action(rec)
                        self._remember(rec)
                        pb = self._playbooks.get(rec["playbook"])
                        if rec["id"] not in verdicts and pb is not None:
                            self._pending.append({
                                "id": rec["id"],
                                "playbook": rec["playbook"],
                                "objective": rec["objective"],
                                "due": float(rec["t"]) + pb.verify_after,
                                "cost0": rec.get("cost0", 0.0)})
                    elif op == "verdict":
                        self._apply_verdict(rec)
                        self._remember(rec)
                    elif op == "disable":
                        self._apply_disable(rec)
                        self._remember(rec)
                    elif op == "enable":
                        self._apply_enable(rec)
                        self._remember(rec)
                    elif op == "state":
                        self._apply_state(rec)
            finally:
                self._replaying = False
        if recs:
            log.info("action journal replayed", kv={"records": len(recs)})
        return len(recs)

    def close(self) -> None:
        self._journal.close()

    # ----------------- read surfaces -----------------

    def _remember(self, rec: dict) -> None:
        self._history.append(rec)
        del self._history[:-self._history_limit]

    def _dump(self, reason: str) -> None:
        if self.recorder is not None and self.dump_dir:
            self.recorder.dump(self.dump_dir, reason=reason)

    def history(self, limit: int = 50) -> List[dict]:
        with self._lock:
            return list(self._history[-int(limit):])

    def actions_total(self) -> int:
        with self._lock:
            return sum(st.actions for st in self._state.values())

    def disabled_playbooks(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._state.items()
                          if st.disabled)

    def fingerprint(self) -> str:
        """Order-independent digest over the JOURNAL-DERIVED state —
        what the shard-SIGKILL replay gate compares pre/post. Playbooks
        that never acted and were never disabled carry no
        journal-observable state and are excluded (a replayed
        controller may register a different playbook set)."""
        with self._lock:
            rows = sorted(
                f"{n}|{st.actions}|{st.paid}|{st.unpaid}|{st.streak}|"
                f"{st.disabled}|{st.disabled_source}|{st.last_t}"
                for n, st in self._state.items()
                if st.actions > 0 or st.disabled)
        return hashlib.sha256("\n".join(rows).encode()).hexdigest()

    def snapshot(self) -> Dict[str, Any]:
        """The scoreboard ``tpuctl remediate`` renders."""
        with self._lock:
            playbooks: Dict[str, Any] = {}
            for name in sorted(set(self._state) | set(self._playbooks)):
                st = self._state.get(name) or _PBState(name)
                pb = self._playbooks.get(name)
                playbooks[name] = {
                    "objective": pb.objective if pb else "",
                    "actions": st.actions,
                    "budget": pb.budget if pb else None,
                    "cooldown": pb.cooldown if pb else None,
                    "paid": st.paid,
                    "unpaid": st.unpaid,
                    "streak": st.streak,
                    "disabled": st.disabled,
                    "disabled_source": st.disabled_source,
                    "last_t": st.last_t,
                    "last_verdict": st.last_verdict,
                    "pending": sum(1 for p in self._pending
                                   if p["playbook"] == name),
                }
            return {
                "playbooks": playbooks,
                "actions": sum(p["actions"] for p in playbooks.values()),
                "paid": sum(p["paid"] for p in playbooks.values()),
                "unpaid": sum(p["unpaid"] for p in playbooks.values()),
                "pending": len(self._pending),
                "disabled": self.disabled_playbooks(),
                "fingerprint": self.fingerprint(),
            }


def remediation_objective(windows: Windows = TICK_WINDOWS,
                          clear_after: int = 2) -> Objective:
    """The watchdog-on-the-watchdog: an objective over the
    ``kftpu_remediation_disabled`` gauge family that PAGES
    ``remediation-disabled[playbook=X]`` while a playbook is disabled —
    the self-healing loop giving itself back to the operator instead of
    flapping. Append it to the engine's objective set wherever a
    RemediationController shares the registry."""
    return Objective(
        name="remediation-disabled",
        description="a remediation playbook auto-disabled (cost "
                    "unrepaid) or was disabled by an operator",
        gauge="kftpu_remediation_disabled",
        group_by="playbook",
        max_value=0.0,
        slo=0.90,
        page_burn=1.5,
        warn_burn=1.0,
        clear_after=clear_after,
        windows=windows,
    )


# --------------------------------------------------------------------------
# Stock playbooks: the five actuation seams, wrapped
# --------------------------------------------------------------------------


def drain_backend_playbook(lb, *, objective: str = "backend-queue-wait",
                           min_live: int = 1, budget: int = 3,
                           cooldown: float = 3.0, verify_after: float = 3.0,
                           unpaid_disable_after: int = 3) -> Playbook:
    """Drain the paged serving backend out of the dispatch set
    (``lb.set_backends`` keeps it draining until in-flight hits zero);
    cache-affine re-route happens on the next dispatch — affinity
    yields to eligibility, so the drained replica's sessions land on
    survivors. Refuses (precheck) to go below ``min_live`` live
    backends: remediation must never drain the fleet dark."""

    def _candidates(rec: dict):
        addr = series_label(rec["objective"])
        current = [b["addr"] for b in lb.backends() if not b["draining"]]
        if addr in current and len(current) - 1 >= min_live:
            return addr, current
        return None, current

    def _precheck(rec: dict) -> bool:
        addr, _ = _candidates(rec)
        return addr is not None

    def _act(rec: dict) -> dict:
        addr, current = _candidates(rec)
        if addr is None:
            return {"skipped": "backend gone or fleet too small"}
        keep = [a for a in current if a != addr]
        lb.set_backends(keep)
        return {"drained": addr, "kept": len(keep)}

    return Playbook(name="drain-backend", objective=objective,
                    action=_act, precheck=_precheck, budget=budget,
                    cooldown=cooldown, verify_after=verify_after,
                    unpaid_disable_after=unpaid_disable_after)


def requeue_playbook(manager, *, objective: str = "goodput-interruptions",
                     within: float = 3600.0, budget: int = 3,
                     cooldown: float = 3.0, verify_after: float = 3.0,
                     cost_budget: float = 0.0,
                     unpaid_disable_after: int = 3) -> Playbook:
    """Fire the PR-8 park path's retry timers now
    (``ControllerManager.kick_timers``): gangs parked on capacity /
    ledger backoff re-attempt admission this tick instead of waiting
    out the park interval — the requeue answer to an interruption
    burst."""

    def _act(rec: dict) -> dict:
        manager.kick_timers(within)
        return {"kicked_within_s": within}

    return Playbook(name="requeue-parked", objective=objective,
                    action=_act, budget=budget, cooldown=cooldown,
                    verify_after=verify_after, cost_budget=cost_budget,
                    unpaid_disable_after=unpaid_disable_after)


def grow_elastic_playbook(elastic, *, objective: str = "tenant-goodput",
                          budget: int = 3, cooldown: float = 3.0,
                          verify_after: float = 3.0,
                          unpaid_disable_after: int = 3) -> Playbook:
    """Grow the most-deserving under-sized elastic gang through the
    one growth seam (``ElasticController.sweep`` ->
    ``scheduler.try_grow`` + commit) — the VirtualFlow move: remediate
    by resize, not restart."""

    def _act(rec: dict) -> dict:
        return {"grown": int(elastic.sweep())}

    return Playbook(name="grow-elastic", objective=objective,
                    action=_act, budget=budget, cooldown=cooldown,
                    verify_after=verify_after,
                    unpaid_disable_after=unpaid_disable_after)


def shrink_gang_playbook(api, pick_victim, *,
                         objective: str = "queue-age",
                         budget: int = 2, cooldown: float = 4.0,
                         verify_after: float = 4.0,
                         cost_budget: float = 4.0,
                         unpaid_disable_after: int = 2) -> Playbook:
    """Shrink (or free for migration) one slice group of a victim gang
    through the ONE eviction seam
    (``scheduler.preempt.preempt_slice_group``) — never ad-hoc pod
    deletion. ``pick_victim() -> (job, group) | None`` owns the policy
    (lowest priority above its elastic floor, defrag's
    ``_pick_migration`` choice, ...); eviction has a real ledger cost,
    so the default ``cost_budget`` is nonzero and the disable trigger
    tight."""

    def _precheck(rec: dict) -> bool:
        return pick_victim() is not None

    def _act(rec: dict) -> dict:
        victim = pick_victim()
        if victim is None:
            return {"skipped": "no eligible victim"}
        from kubeflow_tpu.scheduler.preempt import preempt_slice_group
        job, group = victim
        n = preempt_slice_group(api, job, group)
        return {"job": f"{job.metadata.namespace}/{job.metadata.name}",
                "group": group, "pods": n}

    return Playbook(name="shrink-gang", objective=objective,
                    action=_act, precheck=_precheck, budget=budget,
                    cooldown=cooldown, verify_after=verify_after,
                    cost_budget=cost_budget,
                    unpaid_disable_after=unpaid_disable_after)


def respawn_shard_playbook(plane, *, objective: str = "watch-delivery-lag",
                           budget: int = 2, cooldown: float = 4.0,
                           verify_after: float = 4.0,
                           cost_budget: float = 4.0,
                           unpaid_disable_after: int = 2) -> Playbook:
    """Restart a wedged shard through ``ShardedControlPlane``'s
    kill/restart respawn — WAL + journal replay is the recovery
    mechanism, so the restart is safe by construction (the ISSUE-6
    contract). The paging series must carry the ``shNN:`` prefix
    ``slo_union`` adds; an unprefixed series means the caller wired
    this playbook to a non-sharded engine, and the precheck refuses."""

    def _shard_of(rec: dict) -> Optional[int]:
        head, sep, _ = rec["objective"].partition(":")
        if sep and head.startswith("sh") and head[2:].isdigit():
            return int(head[2:])
        return None

    def _precheck(rec: dict) -> bool:
        return _shard_of(rec) is not None

    def _act(rec: dict) -> dict:
        sid = _shard_of(rec)
        if sid is None:
            return {"skipped": "series carries no shard prefix"}
        plane.kill(sid)
        plane.restart(sid)
        return {"respawned_shard": sid}

    return Playbook(name="respawn-shard", objective=objective,
                    action=_act, precheck=_precheck, budget=budget,
                    cooldown=cooldown, verify_after=verify_after,
                    cost_budget=cost_budget,
                    unpaid_disable_after=unpaid_disable_after)
