"""kftpu-prof: the deterministic data-plane step profiler (ISSUE 19).

The platform watches itself from the outside (SLO engine, goodput
ledger, control-plane tracing) but has been blind *inside* the step:
"tok/s dropped" with nothing to say where. This module decomposes every
train step and every serving engine step into phases and attributes
cost to them — the lens the TPU-concurrency study (arxiv 2011.03641)
uses to explain step time, and the collective-bytes baseline ROADMAP
item 2 (EQuARX, arxiv 2506.17615) needs before quantized allreduce can
claim a bandwidth win.

Design rules, in order of precedence:

1. **Conservation by construction.** A step handle samples the clock
   once at ``start_step`` and once per ``mark(phase)``; each phase is
   the half-open interval since the previous mark, and the step span is
   ``[t0, last_mark]``. Phase durations therefore *tile* the step —
   ``sum(phase) == step`` is an identity, not an aspiration — and the
   regression gate checks it as an integer-domain invariant.

2. **One clock seam, two domains.** ``now_fn`` defaults to
   ``time.monotonic`` (the injection seam itself; this module is in the
   KF101 tick domain so no wall-clock *call* appears here). Production
   passes nothing and gets real seconds; seeded scenarios pass a
   :class:`TickClock` and get byte-deterministic integer ticks — every
   clock read costs exactly one tick, so phase durations become event
   counts and the whole profile (and its perfetto export) is
   reproducible byte-for-byte.

3. **Zero overhead when off.** A disabled profiler hands out the
   :data:`NULL_STEP` singleton whose methods are no-ops; hot loops
   guard with ``if h is not None``. Importing this module imports no
   jax — the cost-catalog builders lazy-import ``train/flops.py`` and
   friends only when called (asserted by test).

4. **No wall-clock absolutes in gates.** :func:`profile_gate_failures`
   compares phase *fractions* (one-sided: a phase that grew its share
   beyond budget is a regression; the complement shrink is not) plus
   count-based structure (steps observed, phases present,
   conservation). Chaos latency injected into one phase therefore trips
   exactly that phase — the non-vacuity contract the CI ``prof-smoke``
   stage asserts both ways.

Perfetto export: :meth:`Profiler.export_perfetto` writes Chrome
trace-event JSON — one process per track ("train", "serve"), one thread
per phase, counter tracks for the HBM/KV occupancy samples. Tracer span
ids embed a per-process random stamp (utils/tracing.py), so the export
serialises only ring data (ticks, names, step numbers) — never raw
span ids — to stay byte-identical across processes.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.utils import tracing

# Canonical phase order — also the perfetto thread (track) order, so
# exports are stable even when phases first appear in different orders.
TRAIN_PHASES: Tuple[str, ...] = (
    "data_load", "host_to_device", "step_compute", "eval",
    "checkpoint_save",
)
SERVING_PHASES: Tuple[str, ...] = (
    "queue_wait", "prefill", "decode_chunk", "block_gather", "sample",
    "retire",
)
_PHASE_ORDER: Tuple[str, ...] = TRAIN_PHASES + SERVING_PHASES

#: Host-side phase durations span ~100us (a mark around a dict build)
#: to ~10s (a checkpoint save); the SLO-engine default latency buckets
#: stop at 10s which is fine, but phases need the fine low end.
PHASE_SECONDS_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class TickClock:
    """Deterministic logical clock: every call returns the current tick
    and advances by ``step``. Injected as ``now_fn`` it puts the whole
    profile in an integer tick domain where a phase's duration equals
    the number of clock reads it contained — seeded runs become
    byte-reproducible, which is what the CI gate diffs."""

    def __init__(self, start: int = 0, step: int = 1):
        self._t = int(start)
        self._step = int(step)

    def __call__(self) -> int:
        t = self._t
        self._t += self._step
        return t

    def advance(self, n: int) -> None:
        """Consume ``n`` extra ticks (simulated latency)."""
        self._t += int(n) * self._step

    def peek(self) -> int:
        return self._t


class _NullStep:
    """No-op step handle handed out by a disabled profiler so hot loops
    pay one attribute lookup and a no-op call, nothing else."""

    __slots__ = ()

    def mark(self, phase: str) -> None:  # pragma: no cover - trivial
        return None


NULL_STEP = _NullStep()


class _Step:
    """An open step: phases accumulate as (name, start, duration) tiles
    between consecutive clock samples. Not thread-safe — one handle per
    driving loop, which is how both runners use it."""

    __slots__ = ("prof", "track", "step", "trace_id", "t0", "last",
                 "phases")

    def __init__(self, prof: "Profiler", track: str, step: int,
                 trace_id: str, t0) -> None:
        self.prof = prof
        self.track = track
        self.step = step
        self.trace_id = trace_id
        self.t0 = t0
        self.last = t0
        self.phases: List[Tuple[str, Any, Any]] = []

    def mark(self, phase: str) -> None:
        """Close the phase running since the previous mark (or step
        start). Chaos latency for this phase is injected *before* the
        closing sample so the extra ticks land inside the phase."""
        prof = self.prof
        extra = prof.chaos_extra_ticks.get(phase, 0)
        for _ in range(extra):
            prof._now()
        t = prof._now()
        self.phases.append((phase, self.last, t - self.last))
        self.last = t


class Profiler:
    """Low-overhead phase profiler over the existing Tracer seam.

    Ring buffers (phase, step, counter) are bounded deques stamped with
    a per-profiler monotone ``seq``; :meth:`summary` reports how many
    steps fell off the ring (no silent caps). ``flight=`` attaches this
    profiler to a FlightRecorder sharing the same ``now_fn`` clock
    domain (``attach_profiler``): alert-page and guard dumps then
    append the recent phase ring, so SLO pages arrive with step-phase
    evidence and ``stitch()`` ordering holds by construction.
    """

    def __init__(self, *, enabled: bool = True,
                 tracer: Optional[tracing.Tracer] = None,
                 registry=None,
                 now_fn: Optional[Callable[[], Any]] = None,
                 shard: str = "",
                 capacity: int = 4096,
                 flight=None,
                 chaos_extra_ticks: Optional[Dict[str, int]] = None):
        self.enabled = bool(enabled)
        self.shard = shard
        # Reference-only default: the KF101 injection seam.
        self._now = now_fn if now_fn is not None else time.monotonic
        self.tracer = tracer
        self.flight = flight
        if self.enabled and flight is not None:
            flight.attach_profiler(self)
        self.chaos_extra_ticks = dict(chaos_extra_ticks or {})
        cap = max(int(capacity), 1)
        # Phases outnumber steps ~6:1; size the step/counter rings down
        # so a full phase ring never strands step records whose phases
        # were already evicted more than transiently.
        self._phases: deque = deque(maxlen=cap)
        self._steps: deque = deque(maxlen=cap)
        self._counters: deque = deque(maxlen=cap)
        # Lifetime finished-step count per track: the ring may evict but
        # the LEDGER may not — summary()'s steps_dropped is derived from
        # this total, so eviction is always visible (no silent caps).
        self._finished: Dict[str, int] = {}
        self._seq = 0
        self._catalog: Dict[str, Dict[str, Any]] = {}
        self._run_trace_id = ""
        self._hist_train = self._hist_serve = self._mfu_gauge = None
        if self.enabled and registry is not None:
            self._hist_train = registry.histogram(
                "kftpu_train_phase_seconds",
                "Train step time decomposed by phase (profiler tiles).",
                buckets=PHASE_SECONDS_BUCKETS, labels=("phase",))
            self._hist_serve = registry.histogram(
                "kftpu_serving_phase_seconds",
                "Serving engine step time decomposed by phase.",
                buckets=PHASE_SECONDS_BUCKETS, labels=("phase",))
            self._mfu_gauge = registry.gauge(
                "kftpu_train_mfu_ratio",
                "Model-FLOPs utilization: achieved model FLOP/s over "
                "device peak (0 when the peak is unknown).")

    # ----------------------------- stepping -----------------------------

    def start_step(self, track: str, step: int, *, trace_id: str = ""):
        """Open a step on ``track`` ("train"/"serve"). Returns a handle
        whose :meth:`_Step.mark` closes consecutive phases; a disabled
        profiler returns :data:`NULL_STEP` without reading the clock."""
        if not self.enabled:
            return NULL_STEP
        return _Step(self, track, int(step), trace_id, self._now())

    def finish_step(self, handle) -> Optional[Dict[str, Any]]:
        """Close the step: ring the phases + step record, observe the
        phase histograms, emit tracer spans under the adopted trace id,
        and (if attached) land one flight-recorder entry."""
        if handle is NULL_STEP or handle is None or not self.enabled:
            return None
        h = handle
        step_dur = h.last - h.t0
        hist = (self._hist_train if h.track == "train"
                else self._hist_serve if h.track == "serve" else None)
        trace_id = h.trace_id or self._run_trace()
        by_phase: Dict[str, Any] = {}
        for phase, t0, dur in h.phases:
            self._seq += 1
            rec = {"track": h.track, "phase": phase, "step": h.step,
                   "t": t0, "dur": dur, "seq": self._seq}
            self._phases.append(rec)
            by_phase[phase] = by_phase.get(phase, 0) + dur
            if hist is not None:
                hist.observe(float(dur), exemplar=trace_id, phase=phase)
            if self.tracer is not None:
                s = self.tracer.start(
                    f"{h.track}/{phase}", trace_id=trace_id,
                    attrs={"step": h.step, "tick": t0, "ticks": dur,
                           "shard": self.shard})
                self.tracer.finish(s)
        self._seq += 1
        srec = {"track": h.track, "step": h.step, "t": h.t0,
                "dur": step_dur, "seq": self._seq, "phases": by_phase}
        self._steps.append(srec)
        self._finished[h.track] = self._finished.get(h.track, 0) + 1
        return srec

    def request_event(self, name: str, trace_id: str, *,
                      attrs: Optional[Dict[str, Any]] = None):
        """Emit an instant span under an *existing* request/job trace id
        (``req:<n>`` / job names) so the phase evidence stitches into
        the timelines ``tpuctl trace --id`` already renders."""
        if not self.enabled or self.tracer is None:
            return None
        s = self.tracer.start(name, trace_id=trace_id,
                              attrs=dict(attrs or {}))
        self.tracer.finish(s)
        return s

    def sample_counters(self, values: Dict[str, float], *,
                        track: str = "serve",
                        step: Optional[int] = None) -> None:
        """Sample counter-track values (HBM occupancy, blocks shared,
        scratch pressure) at one clock read — a single timeline tick
        shared by all the values in this sample."""
        if not self.enabled or not values:
            return
        t = self._now()
        for name in sorted(values):
            self._seq += 1
            self._counters.append(
                {"track": track, "name": name, "t": t,
                 "value": float(values[name]), "step": step,
                 "seq": self._seq})

    def _run_trace(self) -> str:
        """One root span per profiler run: steps with no request/job id
        of their own share its trace id, forming a single timeline."""
        if not self._run_trace_id:
            if self.tracer is None:
                self._run_trace_id = "profile:run"
            else:
                s = self.tracer.start("profile/run",
                                      attrs={"shard": self.shard})
                self.tracer.finish(s)
                self._run_trace_id = s.trace_id
        return self._run_trace_id

    # --------------------------- cost catalog ---------------------------

    @property
    def catalog(self) -> Dict[str, Dict[str, Any]]:
        return self._catalog

    def set_catalog(self, catalog: Dict[str, Dict[str, Any]]) -> None:
        """Attach a per-compiled-fn cost catalog (see
        :func:`train_cost_catalog` / :func:`serving_cost_catalog`);
        merged, not replaced, so train and serving catalogs compose."""
        self._catalog.update(catalog)

    def set_train_mfu(self, *, tokens_per_sec: float,
                      flops_per_token: float,
                      peak_tflops: Optional[float] = None) -> float:
        """Publish achieved MFU to ``kftpu_train_mfu_ratio`` and the
        catalog. ``peak_tflops=None`` asks the device (lazy jax import);
        an unknown peak (CPU) reports 0 rather than a fiction."""
        if peak_tflops is None:
            from kubeflow_tpu.train.flops import device_peak_tflops
            peak_tflops = device_peak_tflops()
        ratio = 0.0
        if peak_tflops and peak_tflops > 0:
            ratio = (tokens_per_sec * flops_per_token
                     / (peak_tflops * 1e12))
        entry = self._catalog.setdefault("train_step", {})
        entry["mfu"] = ratio
        entry["peak_tflops"] = float(peak_tflops or 0.0)
        if self._mfu_gauge is not None:
            self._mfu_gauge.set(ratio)
        return ratio

    # ------------------------- read/export surface -----------------------

    def recent_phases(self, n: int = 64) -> List[Dict[str, Any]]:
        """Newest-last tail of the phase ring — the slice FlightRecorder
        dumps append as SLO-page evidence (bounded by ``n``)."""
        if n <= 0:
            return []
        return list(self._phases)[-int(n):]

    def summary(self) -> Dict[str, Any]:
        """Per-track rollup over *complete* steps (steps whose phase
        tiles are still fully resident in the ring): total ticks, ticks
        and fraction per phase, the conservation verdict, and how many
        steps fell off the ring (no silent caps)."""
        oldest_phase_t = self._phases[0]["t"] if self._phases else None
        phases_by_step: Dict[Tuple[str, int], Any] = {}
        for rec in self._phases:
            key = (rec["track"], rec["step"])
            phases_by_step[key] = phases_by_step.get(key, 0) + rec["dur"]
        out: Dict[str, Any] = {}
        # Every track that ever finished a step appears, even if the
        # rings have since evicted all of it — the lifetime ledger is
        # what keeps eviction visible.
        for track in self._finished:
            out[track] = {
                "steps": 0, "steps_dropped": 0, "step_ticks": 0,
                "phase_ticks": {}, "fractions": {},
                "conservation_ok": True,
            }
        complete_keys = set()
        for srec in self._steps:
            track = srec["track"]
            tr = out[track]
            complete = (oldest_phase_t is not None
                        and not srec["t"] < oldest_phase_t)
            if not complete:
                continue
            complete_keys.add((track, srec["step"]))
            tr["steps"] += 1
            tr["step_ticks"] += srec["dur"]
            dur = srec["dur"]
            tiled = phases_by_step.get((track, srec["step"]), 0)
            if isinstance(dur, int) and isinstance(tiled, int):
                ok = tiled == dur      # tick domain: exact identity
            else:                      # wall clock: telescoped floats
                ok = abs(tiled - dur) <= 1e-9 + 1e-6 * abs(dur)
            if not ok:
                tr["conservation_ok"] = False
        for rec in self._phases:
            if (rec["track"], rec["step"]) not in complete_keys:
                continue
            ticks = out[rec["track"]]["phase_ticks"]
            ticks[rec["phase"]] = ticks.get(rec["phase"], 0) + rec["dur"]
        for track, tr in out.items():
            tr["steps_dropped"] = (self._finished.get(track, 0)
                                   - tr["steps"])
            total = tr["step_ticks"]
            if total:
                tr["fractions"] = {
                    p: t / total for p, t in sorted(tr["phase_ticks"].items())
                }
        return out

    def to_dict(self) -> Dict[str, Any]:
        """The whole profile as one JSON-able dict — what ``tpuctl
        profile record`` saves and ``show``/``export`` read back."""
        return {
            "version": 1,
            "shard": self.shard,
            "phases": list(self._phases),
            "steps": list(self._steps),
            "counters": list(self._counters),
            "catalog": self._catalog,
            "summary": self.summary(),
        }

    def export_perfetto(self, path: Optional[str] = None) -> str:
        """Chrome trace-event JSON of the rings (see
        :func:`perfetto_json`); optionally written to ``path``."""
        text = perfetto_json(self.to_dict())
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text


# -------------------------- perfetto rendering ---------------------------

def _phase_tid(phase: str, extra: Dict[str, int]) -> int:
    try:
        return 1 + _PHASE_ORDER.index(phase)
    except ValueError:
        return extra.setdefault(phase,
                                1 + len(_PHASE_ORDER) + len(extra))


def perfetto_json(data: Dict[str, Any]) -> str:
    """Render a :meth:`Profiler.to_dict` profile as Chrome trace-event
    JSON (the format Perfetto/chrome://tracing open directly).

    Layout: one *process* per track ("train", "serve" — per
    replica/shard, named ``track:shard``), one *thread* per phase in
    canonical order, thread 0 carrying the step spans, plus one counter
    track per sampled counter name. Only ring data is serialised —
    ticks, names, step numbers — never tracer span ids (those embed a
    per-process random stamp and would break byte determinism). Output
    is fully sorted and separator-canonical: same profile, same bytes.
    """
    shard = data.get("shard", "")
    tracks = sorted({r["track"] for r in data.get("steps", [])}
                    | {r["track"] for r in data.get("phases", [])}
                    | {r["track"] for r in data.get("counters", [])})
    pid_of = {t: i + 1 for i, t in enumerate(tracks)}
    events: List[Dict[str, Any]] = []
    extra_tids: Dict[str, int] = {}
    phase_threads = set()
    for rec in data.get("phases", []):
        pid = pid_of[rec["track"]]
        tid = _phase_tid(rec["phase"], extra_tids)
        phase_threads.add((pid, tid, rec["phase"]))
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "name": rec["phase"],
            "cat": rec["track"], "ts": rec["t"], "dur": rec["dur"],
            "args": {"step": rec["step"]},
        })
    for rec in data.get("steps", []):
        pid = pid_of[rec["track"]]
        events.append({
            "ph": "X", "pid": pid, "tid": 0, "name": "step",
            "cat": rec["track"], "ts": rec["t"], "dur": rec["dur"],
            "args": {"step": rec["step"]},
        })
    for rec in data.get("counters", []):
        pid = pid_of[rec["track"]]
        events.append({
            "ph": "C", "pid": pid, "tid": 0, "name": rec["name"],
            "ts": rec["t"], "args": {"value": rec["value"]},
        })
    meta: List[Dict[str, Any]] = []
    for track, pid in sorted(pid_of.items(), key=lambda kv: kv[1]):
        pname = f"{track}:{shard}" if shard else track
        meta.append({"ph": "M", "pid": pid, "tid": 0,
                     "name": "process_name",
                     "args": {"name": pname}})
        meta.append({"ph": "M", "pid": pid, "tid": 0,
                     "name": "thread_name", "args": {"name": "step"}})
    for pid, tid, phase in sorted(phase_threads):
        meta.append({"ph": "M", "pid": pid, "tid": tid,
                     "name": "thread_name", "args": {"name": phase}})
    events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"],
                               e.get("dur", 0)))
    doc = {
        "displayTimeUnit": "ms",
        "traceEvents": meta + events,
        "metadata": {"kftpu_profile_version": data.get("version", 1)},
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def perfetto_track_counts(text: str) -> Dict[str, int]:
    """Structural census of a perfetto export: distinct phase tracks
    (named threads other than "step") and counter tracks — the counts
    the acceptance gate asserts (>=4 phase, >=2 counter for a seeded
    serving run)."""
    doc = json.loads(text)
    phase_tracks = set()
    counter_tracks = set()
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            name = ev.get("args", {}).get("name", "")
            if name and name != "step":
                phase_tracks.add((ev["pid"], name))
        elif ev.get("ph") == "C":
            counter_tracks.add((ev["pid"], ev["name"]))
    return {"phase_tracks": len(phase_tracks),
            "counter_tracks": len(counter_tracks)}


# --------------------------- regression gate -----------------------------

def profile_gate_failures(summary: Dict[str, Any],
                          baseline: Dict[str, Any], *,
                          default_budget: float = 0.08) -> List[str]:
    """Phase-budget regression gate: compare a :meth:`Profiler.summary`
    against a recorded baseline (PROFILE_r19.json ``gates`` section).

    Checks, all count/ratio-based (never wall-clock-absolute):

    - zero-observation guard: a track in the baseline with no observed
      steps fails loudly (a gate that can pass on nothing is KF105's
      bug class, and this one cannot);
    - conservation: phase tiles must sum to the step span on every
      complete step;
    - phase presence: every baseline phase must have been observed;
    - phase-fraction regression, ONE-SIDED: a phase whose share of step
      time *grew* more than its budget over the baseline fraction
      fails. One-sided is what makes chaos injection surgical — ticks
      added to one phase shrink every other phase's share, and shrinking
      is the complement of the regression, not a second regression.
    """
    failures: List[str] = []
    for track in sorted(baseline):
        base = baseline[track]
        s = summary.get(track)
        if s is None or s.get("steps", 0) == 0:
            failures.append(f"{track}: no profiled steps observed "
                            "(gate would be vacuous)")
            continue
        if not s.get("conservation_ok", False):
            failures.append(f"{track}: phase/step conservation violated")
        base_fracs = base.get("phase_fractions", {})
        if len(base_fracs) == 0:
            failures.append(f"{track}: baseline has no phase fractions "
                            "(vacuous baseline)")
            continue
        budgets = base.get("phase_budgets", {})
        fracs = s.get("fractions", {})
        for phase in sorted(base_fracs):
            bf = float(base_fracs[phase])
            budget = float(budgets.get(phase, base.get(
                "budget", default_budget)))
            f = fracs.get(phase)
            if f is None:
                failures.append(f"{track}.{phase}: phase absent from "
                                "profile (baseline expects it)")
                continue
            if f - bf > budget:
                failures.append(
                    f"{track}.{phase}: fraction {f:.4f} grew past "
                    f"baseline {bf:.4f} + budget {budget:.4f}")
    return failures


# ---------------------------- cost catalogs ------------------------------

def train_cost_catalog(cfg: Any, *, seq_len: int, global_batch: int,
                       mesh_axes: Optional[Dict[str, int]] = None,
                       param_bytes: Optional[int] = None,
                       measured: Optional[Dict[str, float]] = None,
                       moe: bool = False) -> Dict[str, Dict[str, Any]]:
    """Analytic cost entry for the compiled train step: model FLOPs
    (train = 3x fwd, causal), gradient-allreduce bytes by mesh axis
    (first-order ring model from ``parallel/costs.py``), optionally the
    XLA-measured dict from ``Trainer.step_cost_analysis`` under
    ``measured`` (kept verbatim; XLA's numbers vary across versions so
    goldens pin only the analytic side). Lazy imports: calling this —
    not importing this module — pulls jax-adjacent code."""
    from kubeflow_tpu.parallel.costs import allreduce_bytes_by_axis
    from kubeflow_tpu.train.flops import (llama_matmul_params,
                                          moe_matmul_params_active,
                                          train_flops_per_token)
    tokens = int(global_batch) * int(seq_len)
    fpt = train_flops_per_token(cfg, seq_len, moe=moe)
    n_params = (moe_matmul_params_active(cfg) if moe
                else llama_matmul_params(cfg))
    grad_bytes = int(param_bytes) if param_bytes is not None \
        else 4 * n_params
    entry: Dict[str, Any] = {
        "fn": "train_step",
        "flops_per_token": fpt,
        "tokens_per_call": tokens,
        "flops": fpt * tokens,
        "matmul_params": n_params,
        "collective_bytes": allreduce_bytes_by_axis(
            grad_bytes, mesh_axes or {}),
    }
    if measured:
        entry["measured"] = {k: float(v) for k, v in measured.items()}
    return {"train_step": entry}


def serving_cost_catalog(cfg: Any, *, context_len: int,
                         kv_block_size: int, blocks_per_seq: int,
                         batch: int, kv_dtype_bytes: int = 2,
                         ) -> Dict[str, Dict[str, Any]]:
    """Analytic cost entries for the serving compiled fns: per-token
    forward FLOPs for prefill (full-context attention) and decode
    (attention against the whole cache), and bytes moved per
    block-gather dispatch (``ops/paged_attention.py`` cost fn — the
    residency bill the paged pool pays each decode step)."""
    from kubeflow_tpu.ops.paged_attention import paged_gather_bytes
    from kubeflow_tpu.train.flops import serving_flops_per_token
    prefill_fpt = serving_flops_per_token(cfg, context_len)
    decode_fpt = serving_flops_per_token(cfg, context_len, causal=False)
    gather = paged_gather_bytes(
        num_layers=cfg.num_layers, batch=batch,
        blocks_per_seq=blocks_per_seq, block_size=kv_block_size,
        num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
        dtype_bytes=kv_dtype_bytes)
    return {
        "prefill": {"fn": "prefill", "flops_per_token": prefill_fpt,
                    "context_len": int(context_len)},
        "decode_chunk": {"fn": "decode_chunk",
                         "flops_per_token": decode_fpt,
                         "batch": int(batch)},
        "block_gather": {"fn": "gather_kv_pages",
                         "bytes_per_dispatch": gather,
                         "blocks_per_seq": int(blocks_per_seq),
                         "kv_block_size": int(kv_block_size)},
    }


# --------------------------- seeded scenarios ----------------------------

def seeded_serving_profile(*, seed: int = 0, requests: int = 4,
                           max_new_tokens: int = 6,
                           chaos_extra_ticks: Optional[Dict[str, int]]
                           = None,
                           registry=None, tracer=None, flight=None,
                           ) -> Profiler:
    """Drive a tiny Llama through the real serving engine with a
    :class:`TickClock` profiler attached — the shared seeded scenario
    behind ``tests/test_profiler.py``, ``tpuctl profile record`` and
    the CI ``prof-smoke`` gate. Deterministic: fixed seed, fixed
    prompts, integer tick domain; two runs export byte-identical
    perfetto JSON. Lazy-imports jax (module import stays jax-free)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Llama, LlamaConfig
    from kubeflow_tpu.serving import ServingConfig, ServingEngine

    block, max_len = 8, 64
    kv_blocks = 4 * (max_len // block)
    cfg = LlamaConfig.tiny(max_seq_len=128, paged_kv_blocks=kv_blocks,
                           paged_kv_block_size=block)
    model = Llama(cfg)
    params = {"params": model.init(
        jax.random.PRNGKey(seed), jnp.ones((1, 8), jnp.int32))["params"]}
    prof = Profiler(now_fn=TickClock(), registry=registry, tracer=tracer,
                    flight=flight, chaos_extra_ticks=chaos_extra_ticks)
    engine = ServingEngine(
        model, params,
        ServingConfig(max_batch=2, max_len=max_len, kv_blocks=kv_blocks,
                      kv_block_size=block),
        profiler=prof)
    prof.set_catalog(serving_cost_catalog(
        cfg, context_len=max_len, kv_block_size=block,
        blocks_per_seq=engine.blocks.blocks_for_tokens(max_len),
        batch=2))
    # One block-aligned shared prefix across all requests: COW prefix
    # sharing engages, so the kv_blocks_shared counter track is
    # non-vacuous and a write-fork exercises the paged path.
    head = [3 + seed % 5] * block
    for i in range(int(requests)):
        prompt = head + [2 + (seed + i) % 7, 5 + i % 3, 9]
        engine.submit(prompt, max_new_tokens=max_new_tokens)
    engine.run()
    return prof


def seeded_train_profile(*, steps: int = 4, seed: int = 0,
                         checkpoint_every: int = 2,
                         chaos_extra_ticks: Optional[Dict[str, int]]
                         = None,
                         registry=None, tracer=None, flight=None,
                         ) -> Profiler:
    """Tiny training loop (real Trainer, synthetic text batches) under
    a :class:`TickClock` profiler: data_load / host_to_device /
    step_compute per step plus checkpoint_save every
    ``checkpoint_every`` steps (marked without touching disk — the
    phase timeline is the subject here, not the checkpoint codec)."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Llama, LlamaConfig
    from kubeflow_tpu.topology.mesh import AxisSpec, make_host_local_mesh
    from kubeflow_tpu.train import TrainConfig, Trainer
    from kubeflow_tpu.train.data import SyntheticTextConfig, synthetic_text

    cfg = LlamaConfig.tiny(max_seq_len=32)
    # dp over all local devices (1 on a bare CPU run, 8 under the test
    # harness's virtual devices): device count changes the mesh, never
    # the tick counts — ticks are clock reads, and the mark sequence is
    # identical — so the recorded baseline holds in both environments.
    ndev = jax.device_count()
    mesh = make_host_local_mesh(AxisSpec(dp=-1))
    trainer = Trainer(
        Llama(cfg),
        TrainConfig(task="lm", learning_rate=1e-3, warmup_steps=2,
                    total_steps=max(int(steps), 3)),
        mesh)
    it = synthetic_text(SyntheticTextConfig(
        batch_size=2 * ndev, seq_len=16, vocab_size=cfg.vocab_size,
        seed=seed))
    batch0 = trainer.shard_batch(
        {k: jnp.asarray(v) for k, v in next(it).items()})
    state = trainer.init_state(jax.random.PRNGKey(seed), batch0)
    prof = Profiler(now_fn=TickClock(), registry=registry, tracer=tracer,
                    flight=flight, chaos_extra_ticks=chaos_extra_ticks)
    prof.set_catalog(train_cost_catalog(
        cfg, seq_len=16, global_batch=2 * ndev, mesh_axes={"dp": ndev}))
    for i in range(int(steps)):
        h = prof.start_step("train", i)
        raw = next(it)
        h.mark("data_load")
        batch = trainer.shard_batch(
            {k: jnp.asarray(v) for k, v in raw.items()})
        h.mark("host_to_device")
        state, _ = trainer.step(state, batch)
        h.mark("step_compute")
        if checkpoint_every and (i + 1) % checkpoint_every == 0:
            h.mark("checkpoint_save")
        prof.finish_step(h)
    return prof
