"""Crash-dump flight recorder (ISSUE 15).

When a soak gate trips, an alert pages, or a shard dies, the evidence —
the last few hundred watch events, spans, metric movements — is exactly
what a human (or the CI log) needs and exactly what was gone by the time
anyone looked. The :class:`FlightRecorder` keeps a bounded, causally
ordered ring of recent happenings per process and dumps it to a
``flight-*.jsonl`` file on demand or on trigger:

- **alert page** — the SLO engine (obs/slo.py) dumps on every ok/warn →
  page transition;
- **conservation-gate failure** — a registered guard (the goodput
  ledger's exact-conservation check) flipping false dumps once;
- **shard SIGKILL respawn** — a shard worker that replayed a WAL on
  start dumps what the fresh incarnation knows under its shard dir;
- **operator demand** — ``tpuctl flight dump``.

Ring entries are ``{"seq", "shard", "t", "kind", "data"[, "trace_id"]}``:
``seq`` is a per-recorder monotone counter (causal order WITHIN a
process is exact), ``t`` is wall-clock (the only cross-process ordering
there is), ``shard`` tags the process. :func:`stitch` merges dumps from
many shards the way the PR-10 trace union merges span files: sort by
``(t, shard, seq)``, dedup on identity — within one shard the order is
causal, across shards it is wall-clock honest.

Everything here is bounded: the ring evicts oldest-first, a dump is at
most ring + a bounded tail of recent tracer spans, and metric-delta
records are capped per sample.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.utils import get_logger

log = get_logger("flight")

FLIGHT_GLOB = "flight-*.jsonl"

#: Spans pulled from the tracer ring into a dump (newest kept).
DUMP_SPAN_TAIL = 256

#: Phase records pulled from an attached profiler's ring into a dump
#: (newest kept) — SLO pages arrive with step-phase evidence attached.
DUMP_PHASE_TAIL = 256

#: Changed counter samples recorded per ``record_metric_deltas`` call.
METRIC_DELTA_CAP = 64


class FlightRecorder:
    """Bounded per-process ring of recent events/spans/metric deltas.

    ``shard`` tags every entry (and the dump header) so cross-shard
    stitches stay attributable; ``tracer`` (optional) contributes its
    newest spans to dumps; ``registry`` (optional) powers
    :meth:`record_metric_deltas`. ``now_fn`` is THE clock for entries
    recorded without an explicit ``t`` — tick-driven drivers hand in
    their logical clock so every ring entry of a process lives in ONE
    clock domain (mixing wall-clock events with tick-stamped alerts
    would scramble the stitched timeline's ``(t, shard, seq)`` order);
    default: wall clock.
    """

    def __init__(self, *, capacity: int = 2048, shard: str = "",
                 tracer=None, registry=None,
                 now_fn: Optional[Any] = None):
        self._now = now_fn or time.time
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=int(capacity))
        self._seq = 0
        self._lock = threading.Lock()
        self.shard = shard
        self.tracer = tracer
        self.registry = registry
        self._metric_base: Dict[tuple, float] = {}
        self._metric_baselined = False
        self._api = None
        self._queue = None
        self._profiler = None
        self.dumps: List[str] = []      # paths written by this recorder
        # Latched guard failures: a guard that flips false dumps ONCE
        # (the conservation gate would otherwise dump every tick until
        # someone fixed the ledger).
        self._guards_tripped: set = set()

    # ----------------- recording -----------------

    def record(self, kind: str, data: Dict[str, Any], *,
               t: Optional[float] = None, trace_id: str = "") -> None:
        entry: Dict[str, Any] = {
            "shard": self.shard,
            "t": round(float(self._now() if t is None else t), 6),
            "kind": kind,
            "data": data,
        }
        if trace_id:
            entry["trace_id"] = trace_id
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)

    def attach(self, api) -> "FlightRecorder":
        """Subscribe to the control plane's full watch stream (one
        kind=None subscription, like the goodput accountant) so
        :meth:`pump` can fold recent object transitions into the ring."""
        self._api = api
        self._queue = api.watch(None)
        return self

    def attach_profiler(self, profiler) -> "FlightRecorder":
        """Attach a step profiler (obs/profiler.py) sharing this
        recorder's ``now_fn`` clock domain: every dump then appends the
        profiler's recent phase ring (bounded by
        :data:`DUMP_PHASE_TAIL`) so an alert page or tripped guard
        lands with the step-phase evidence attached. Phase entries
        carry the PROFILER's monotone seq in the same ``t`` domain, so
        ``stitch()``'s ``(t, shard, seq)`` ordering and its
        ``(shard, seq, kind, t)`` dedup hold unchanged across
        overlapping dumps."""
        self._profiler = profiler
        return self

    def detach(self) -> None:
        if self._api is not None and self._queue is not None:
            try:
                self._api.stop_watch(self._queue)
            except AttributeError:
                pass
            self._queue = None

    def pump(self, *, t: Optional[float] = None) -> int:
        """Drain pending watch events into the ring (non-blocking),
        summarized to one bounded line each. Tick-driven drivers pass
        their logical clock as ``t`` so EVERY ring entry of the process
        lives in one clock domain — mixing wall-clock events with
        tick-stamped metric/alert entries would scramble the stitched
        timeline's (t, shard, seq) order."""
        if self._queue is None:
            return 0
        import queue as _queue

        n = 0
        while True:
            try:
                ev = self._queue.get_nowait()
            except _queue.Empty:
                return n
            obj = getattr(ev, "object", None)
            if obj is None:             # BOOKMARK / RELIST sentinels
                continue
            data = {
                "type": getattr(ev, "type", ""),
                "kind": getattr(obj, "kind", ""),
                "namespace": obj.metadata.namespace,
                "name": obj.metadata.name,
                "rv": obj.metadata.resource_version,
            }
            phase = getattr(getattr(obj, "status", None), "phase", "")
            if phase:
                data["phase"] = phase
            ctx = getattr(ev, "span_ctx", None)
            self.record("event", data, t=t,
                        trace_id=ctx[0] if ctx else "")
            n += 1

    def record_metric_deltas(self, *, t: Optional[float] = None) -> int:
        """Record which ``*_total`` counters moved since the last call
        (one bounded entry), so a dump shows metric MOVEMENT around the
        incident, not just a final snapshot. Returns deltas recorded."""
        if self.registry is None:
            return 0
        first = not self._metric_baselined
        self._metric_baselined = True
        deltas: Dict[str, float] = {}
        for name, labels, value in self.registry.snapshot():
            if not name.endswith("_total"):
                continue
            key = (name, labels)
            prev = self._metric_base.get(key)
            self._metric_base[key] = value
            if first:
                continue            # pure baseline pass
            # A series born after the baseline moved from an implicit 0.
            base = prev if prev is not None else 0.0
            if value == base:
                continue
            if len(deltas) < METRIC_DELTA_CAP:
                lbl = ",".join(f"{k}={v}" for k, v in labels)
                deltas[f"{name}{{{lbl}}}" if lbl else name] = \
                    round(value - base, 6)
        if deltas:
            self.record("metrics", {"deltas": deltas}, t=t)
        return len(deltas)

    # ----------------- guards -----------------

    def check_guards(self, guards: Dict[str, Any],
                     dump_dir: str = "") -> List[str]:
        """Evaluate named guard callables (True = healthy). A guard
        observed False for the FIRST time records a ``guard`` entry and
        — when ``dump_dir`` is set — dumps the ring (latched: one dump
        per guard per process lifetime). Returns the newly tripped
        names."""
        tripped = []
        for name, fn in sorted(guards.items()):
            if name in self._guards_tripped:
                continue
            try:
                ok = bool(fn())
            except Exception as e:  # noqa: BLE001 — a broken guard trips
                ok = False
                self.record("guard_error", {"guard": name,
                                            "error": repr(e)})
            if ok:
                continue
            self._guards_tripped.add(name)
            tripped.append(name)
            self.record("guard", {"guard": name, "ok": False})
            if dump_dir:
                self.dump(dump_dir, reason=f"guard:{name}")
        return tripped

    # ----------------- dumping -----------------

    def dump(self, dir_path: str, *, reason: str = "manual") -> str:
        """Write the ring (plus a bounded tail of recent tracer spans) to
        ``<dir>/flight-<millis>-<n>-<reason>.jsonl``, fsync'd. The
        header line carries the full reason/shard/time; every later
        line is one ring entry or one span. Filenames and the header
        are ALWAYS wall-clock — ring entries keep their caller's clock
        domain, but dump names must sort consistently under one state
        dir no matter which driver (tick or live) wrote them — and the
        slugged reason in the name lets `ls` (and the CI respawn gate)
        tell a shard-respawn dump from an alert-page one without
        opening the file."""
        import re as _re

        os.makedirs(dir_path, exist_ok=True)
        # kftpu: allow(KF101): dump filenames/headers are wall-clock BY
        # CONTRACT (docstring above) — they must sort consistently across
        # tick and live drivers under one state dir; ring ENTRIES keep
        # the injected now_fn clock.
        now = time.time()
        with self._lock:
            entries = list(self._ring)
            n_dumps = len(self.dumps) + 1
        slug = _re.sub(r"[^a-zA-Z0-9_-]+", "-", reason).strip("-")[:48] \
            or "manual"
        fname = (f"flight-{int(now * 1000):013d}-{n_dumps:03d}-"
                 f"{slug}.jsonl")
        path = os.path.join(dir_path, fname)
        spans: List[Dict[str, Any]] = []
        if self.tracer is not None:
            for s in self.tracer.spans()[-DUMP_SPAN_TAIL:]:
                spans.append({"shard": self.shard, "t": s.start_unix,
                              "kind": "span", "seq": 0,
                              "trace_id": s.trace_id,
                              "data": {"name": s.name,
                                       "span_id": s.span_id,
                                       "duration_s": s.duration_s,
                                       "attrs": s.attrs}})
        phases: List[Dict[str, Any]] = []
        if self._profiler is not None:
            for rec in self._profiler.recent_phases(DUMP_PHASE_TAIL):
                phases.append({
                    "shard": self.shard, "t": rec["t"], "kind": "phase",
                    "seq": rec["seq"],
                    "data": {"track": rec["track"],
                             "phase": rec["phase"],
                             "step": rec["step"], "dur": rec["dur"]}})
        header = {"kind": "flight", "reason": reason, "shard": self.shard,
                  "t": round(now, 6), "entries": len(entries),
                  "spans": len(spans), "phases": len(phases), "seq": 0}
        with open(path, "w") as f:
            for rec in [header] + entries + phases + spans:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            self.dumps.append(path)
        log.warning("flight recorder dumped", kv={
            "path": path, "reason": reason, "entries": len(entries),
        })
        return path

    # ----------------- reading / stitching -----------------

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    break       # torn tail: crash mid-write
        return out


def flight_paths(state_dir: str) -> List[str]:
    """Every flight dump under a state dir — the root's own plus each
    shard's (``shard-NN/flight-*.jsonl``), sorted by name (time)."""
    import glob as _glob

    paths = _glob.glob(os.path.join(state_dir, FLIGHT_GLOB))
    paths += _glob.glob(os.path.join(state_dir, "shard-*", FLIGHT_GLOB))
    return sorted(paths)


def stitch(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Merge flight dumps from many processes into ONE causally honest
    timeline: entries sort by ``(t, shard, seq)`` — exact causal order
    within a shard (seq), wall-clock order across shards — and entries
    appearing in overlapping dumps of the same shard dedup on their
    ``(shard, seq, kind, t)`` identity."""
    seen = set()
    out: List[Dict[str, Any]] = []
    for p in paths:
        for rec in FlightRecorder.load(p):
            kind = rec.get("kind", "")
            if kind == "flight":
                rec = dict(rec)
                rec["source"] = os.path.basename(p)
                out.append(rec)      # headers are per-dump, never dedup
                continue
            if kind == "span":
                # Spans carry no ring seq; their own ids identify them.
                ident = (rec.get("shard", ""), "span",
                         rec.get("trace_id", ""),
                         rec.get("data", {}).get("span_id", ""))
            else:
                ident = (rec.get("shard", ""), rec.get("seq", 0),
                         kind, rec.get("t", 0.0))
            if ident in seen:
                continue
            seen.add(ident)
            out.append(rec)
    out.sort(key=lambda r: (r.get("t", 0.0), r.get("shard", ""),
                            r.get("seq", 0)))
    return out
