"""Multi-tenant capacity market (ISSUE 13).

Turns Profiles into a hierarchical tenant tree (org -> team -> user)
and makes every allocation decision fair-share-aware:

- :mod:`~kubeflow_tpu.tenancy.tree` — the quota tree: Profile.spec
  grows ``parent``/``weight``/``goodput_slo``; ``TenantTree`` resolves
  a namespace to its tenant path, validates hierarchical chip quotas
  top-down (a child's quota can never exceed its parent's) and flags
  over-commit (siblings summing past the parent) without forbidding it.
- :mod:`~kubeflow_tpu.tenancy.drf` — weighted dominant-resource fair
  sharing: dominant share = held slice-chips / fleet chips, divided by
  weight; fair fractions split hierarchically by weight among tenants
  with live demand. The scheduler's protection invariant (the bench
  gate): no tenant at-or-below its weighted fair share is ever
  preempted by a tenant above its fair share.
- SLO burn rate (:func:`~kubeflow_tpu.tenancy.drf.slo_burn`): the
  goodput ledger's per-tenant ratio against ``Profile.spec.goodput_slo``
  drives the alert state ``tpuctl tenants`` shows.
"""

from kubeflow_tpu.tenancy.drf import (
    SLO_PAGE_BURN,
    TenantShares,
    compute_shares,
    slo_burn,
    slo_state,
)
from kubeflow_tpu.tenancy.tree import TenantNode, TenantTree

__all__ = [
    "SLO_PAGE_BURN",
    "TenantNode",
    "TenantShares",
    "TenantTree",
    "compute_shares",
    "slo_burn",
    "slo_state",
]
