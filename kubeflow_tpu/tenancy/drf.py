"""Weighted dominant-resource fair sharing + SLO burn math.

The scarce resource the market arbitrates is whole-slice time (the
concurrency-limits measurement of arxiv 2011.03641), so the dominant
resource is SLICE-CHIPS: a tenant's dominant share is the chips its
gangs currently hold divided by the fleet's chips. Weighted DRF divides
that by the tenant's weight; the scheduler keeps every tenant's
weighted share as equal as placement allows by

- admitting the most-deficit tenant's placeable gang first, and
- never letting a tenant ABOVE its fair share evict one at-or-below
  (the protection invariant the bench count-gates — priority still
  breaks ties within a tenant).

Comparisons use an epsilon one chip wide (shares are ratios of small
integers; exact float equality would misread a tenant sitting exactly
at its fair line).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional

from kubeflow_tpu.tenancy.tree import TenantTree

#: Burn rate at which the SLO state escalates from warn to page.
SLO_PAGE_BURN = 2.0


@dataclasses.dataclass
class TenantShares:
    """Point-in-time fair-share ledger over the ACTIVE tenants."""

    shares: Dict[str, float]        # tenant -> held chips / fleet chips
    fair: Dict[str, float]          # tenant -> weighted fair fraction
    held_chips: Dict[str, int]
    total_chips: int

    @property
    def eps(self) -> float:
        # One chip of slack: below that resolution "over" vs "under"
        # is noise, not policy.
        return 1.0 / self.total_chips if self.total_chips > 0 else 1e-9

    def share(self, tenant: str) -> float:
        return self.shares.get(tenant, 0.0)

    def fair_of(self, tenant: str) -> float:
        return self.fair.get(tenant, 0.0)

    def deficit(self, tenant: str) -> float:
        """Fair fraction minus held share: positive = under-served (the
        queue/grow ordering key — biggest deficit first)."""
        return self.fair_of(tenant) - self.share(tenant)

    def at_or_below_fair(self, tenant: str) -> bool:
        return self.share(tenant) <= self.fair_of(tenant) + self.eps

    def over_fair(self, tenant: str) -> bool:
        return self.share(tenant) > self.fair_of(tenant) + self.eps

    def surplus(self, tenant: str) -> float:
        return self.share(tenant) - self.fair_of(tenant)


def compute_shares(
    tree: TenantTree,
    *,
    held_chips: Dict[str, int],
    demanding: Iterable[str] = (),
    total_chips: int,
) -> TenantShares:
    """Build the fair-share ledger: ``held_chips`` maps tenant (leaf
    name == namespace) to chips its gangs hold; ``demanding`` names
    tenants with queued-but-unplaced gangs (active even while holding
    nothing — fair fractions are split only among tenants that want
    capacity, the work-conserving rule)."""
    active = {t for t, c in held_chips.items() if c > 0}
    active.update(demanding)
    fair = tree.fair_fractions(active)
    shares = {
        t: (held_chips.get(t, 0) / total_chips if total_chips > 0 else 0.0)
        for t in active if tree.node(t) is not None
    }
    return TenantShares(
        shares=shares, fair=fair,
        held_chips={t: int(held_chips.get(t, 0)) for t in shares},
        total_chips=int(total_chips),
    )


def slo_burn(goodput_ratio: float, slo: float) -> Optional[float]:
    """Error-budget burn rate: the tenant's badput fraction
    (1 - goodput) over the budget its SLO allows (1 - slo). 1.0 = the
    budget burns exactly at its sustainable rate; above = alerting
    territory. None when no SLO is declared (slo <= 0) or the SLO
    leaves no budget (slo >= 1)."""
    if slo <= 0.0 or slo >= 1.0:
        return None
    return (1.0 - goodput_ratio) / (1.0 - slo)


def slo_state(burn: Optional[float]) -> str:
    """The scoreboard state: ``-`` (no SLO), ``ok`` (inside budget),
    ``warn`` (burning faster than sustainable), ``page`` (burning at
    >= SLO_PAGE_BURN x)."""
    if burn is None:
        return "-"
    if burn <= 1.0:
        return "ok"
    if burn < SLO_PAGE_BURN:
        return "warn"
    return "page"
