"""The hierarchical quota tree rooted at Profiles.

A Profile IS a tenant: its name is the namespace it provisions, its new
``spec.parent`` names another Profile (org -> team -> user chains of any
depth), ``spec.weight`` is its fair-share weight among siblings, and
``spec.tpu_chip_quota`` stays the hierarchical chip ceiling. The tree
resolves every namespace to a tenant *path* (``org/team/user``) — the
label the goodput ledger, the scheduler's fairness invariant and the
serving LB all key on.

Validation is top-down and non-fatal where the platform can keep
running: a child quota larger than its parent's is an ERROR (a child can
never out-quota its subtree's share); siblings whose quotas sum past the
parent are OVER-COMMIT — allowed (the classic borrow-while-idle posture)
but flagged, so ``tpuctl tenants`` and the profile controller surface
it. Unknown parents and cycles degrade to root-attached tenants with a
flag, never a crash: a half-applied org chart must not take scheduling
down with it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass
class TenantNode:
    name: str
    parent: str = ""                  # "" = a root tenant
    weight: float = 1.0
    quota_chips: int = 0              # 0 = unlimited at this level
    goodput_slo: float = 0.0          # 0 = no SLO declared
    children: List[str] = dataclasses.field(default_factory=list)


class TenantTree:
    """Immutable-after-build tenant hierarchy + namespace resolution."""

    def __init__(self, nodes: Dict[str, TenantNode]):
        self._nodes = nodes
        self._flags: List[str] = []
        self._link()

    # ----------------- construction -----------------

    @classmethod
    def from_profiles(cls, profiles: Iterable) -> "TenantTree":
        """Build from live Profile objects (the platform path)."""
        nodes: Dict[str, TenantNode] = {}
        for p in profiles:
            spec = p.spec
            nodes[p.metadata.name] = TenantNode(
                name=p.metadata.name,
                parent=getattr(spec, "parent", "") or "",
                weight=float(getattr(spec, "weight", 1.0) or 1.0),
                quota_chips=int(getattr(spec, "tpu_chip_quota", 0) or 0),
                goodput_slo=float(getattr(spec, "goodput_slo", 0.0) or 0.0),
            )
        return cls(nodes)

    @classmethod
    def from_specs(cls, specs: Iterable[dict]) -> "TenantTree":
        """Build from plain dicts (benches/tests):
        ``{"name": ..., "parent": ..., "weight": ..., "quota_chips": ...,
        "goodput_slo": ...}``."""
        nodes = {
            s["name"]: TenantNode(
                name=s["name"],
                parent=s.get("parent", "") or "",
                weight=float(s.get("weight", 1.0)),
                quota_chips=int(s.get("quota_chips", 0)),
                goodput_slo=float(s.get("goodput_slo", 0.0)),
            )
            for s in specs
        }
        return cls(nodes)

    def _link(self) -> None:
        for n in self._nodes.values():
            if n.weight <= 0:
                self._flags.append(
                    f"tenant {n.name!r}: non-positive weight "
                    f"{n.weight} treated as 1.0")
                n.weight = 1.0
        for n in self._nodes.values():
            if n.parent and n.parent not in self._nodes:
                self._flags.append(
                    f"tenant {n.name!r}: unknown parent {n.parent!r} "
                    "— attached at root")
                n.parent = ""
        # Cycle detection: walk each node to root; a revisit breaks the
        # cycle at the revisited edge (root-attach) and flags it.
        for name in sorted(self._nodes):
            seen = set()
            cur = name
            while cur:
                if cur in seen:
                    self._flags.append(
                        f"tenant cycle through {cur!r} — broken at root")
                    self._nodes[cur].parent = ""
                    break
                seen.add(cur)
                cur = self._nodes[cur].parent
        for n in self._nodes.values():
            n.children = []
        for name in sorted(self._nodes):
            parent = self._nodes[name].parent
            if parent:
                self._nodes[parent].children.append(name)

    # ----------------- lookup -----------------

    def node(self, name: str) -> Optional[TenantNode]:
        return self._nodes.get(name)

    def names(self) -> List[str]:
        return sorted(self._nodes)

    def flags(self) -> List[str]:
        return list(self._flags)

    def roots(self) -> List[str]:
        return sorted(n.name for n in self._nodes.values() if not n.parent)

    def ancestry(self, name: str) -> List[str]:
        """Root-first chain of tenant names ending at ``name``; just
        ``[name]`` for a root; [] for an unknown tenant."""
        if name not in self._nodes:
            return []
        chain = []
        cur: str = name
        while cur:
            chain.append(cur)
            cur = self._nodes[cur].parent
        return list(reversed(chain))

    def resolve(self, namespace: str) -> str:
        """Namespace -> tenant path (``org/team/user``). A namespace
        without a Profile is untenanted: empty string (callers then
        fall back to tenant-blind behaviour, the pre-ISSUE-13 contract)."""
        if namespace not in self._nodes:
            return ""
        return "/".join(self.ancestry(namespace))

    def leaf_of_path(self, path: str) -> str:
        return path.rsplit("/", 1)[-1] if path else ""

    # ----------------- validation -----------------

    def validate(self) -> Tuple[List[str], List[str]]:
        """(errors, overcommits). Errors are spec contradictions (child
        quota > parent quota — a child can never exceed its subtree's
        share); overcommits are allowed-but-flagged (children summing
        past the parent's quota). Build-time flags (unknown parents,
        cycles, bad weights) ride along as errors."""
        errors = list(self._flags)
        overcommit: List[str] = []
        for name in sorted(self._nodes):
            n = self._nodes[name]
            if n.parent:
                pq = self._nodes[n.parent].quota_chips
                if pq > 0 and n.quota_chips > pq:
                    errors.append(
                        f"tenant {name!r}: quota {n.quota_chips} chips "
                        f"exceeds parent {n.parent!r} quota {pq}")
            if n.quota_chips > 0 and n.children:
                child_sum = sum(
                    self._nodes[c].quota_chips for c in n.children)
                if child_sum > n.quota_chips:
                    overcommit.append(
                        f"tenant {name!r}: children declare {child_sum} "
                        f"chips against a quota of {n.quota_chips} "
                        "(over-commit allowed, flagged)")
        return errors, overcommit

    # ----------------- fair shares -----------------

    def fair_fractions(self, active: Iterable[str]) -> Dict[str, float]:
        """Hierarchical weighted fair split of the whole fleet among the
        ``active`` tenants (those with live demand — held capacity or a
        queued gang). At every level, a node's allocation divides among
        its ACTIVE children by weight; a subtree with no active tenant
        gets nothing (its share is available to siblings — work-
        conserving fair sharing, the DRF posture). Returns
        {tenant_name: fraction} for active tenants, summing to 1.0
        (empty when nothing is active)."""
        active_set = {a for a in active if a in self._nodes}
        if not active_set:
            return {}
        live_subtree: Dict[str, bool] = {}

        def subtree_active(name: str) -> bool:
            if name in live_subtree:
                return live_subtree[name]
            n = self._nodes[name]
            alive = name in active_set or any(
                subtree_active(c) for c in n.children)
            live_subtree[name] = alive
            return alive

        out: Dict[str, float] = {}

        def spread(name: str, fraction: float) -> None:
            n = self._nodes[name]
            live_children = [c for c in n.children if subtree_active(c)]
            # An ACTIVE node with active children keeps the weight-share
            # it would have as one more sibling of its own children —
            # the org's direct workloads compete with its teams.
            claimants = list(live_children)
            self_claims = name in active_set
            total_w = sum(self._nodes[c].weight for c in claimants)
            if self_claims:
                total_w += n.weight
            if not claimants:
                if self_claims:
                    out[name] = out.get(name, 0.0) + fraction
                return
            if self_claims and total_w > 0:
                out[name] = out.get(name, 0.0) + \
                    fraction * n.weight / total_w
            for c in claimants:
                spread(c, fraction * self._nodes[c].weight / total_w
                       if total_w > 0 else 0.0)

        live_roots = [r for r in self.roots() if subtree_active(r)]
        root_w = sum(self._nodes[r].weight for r in live_roots)
        for r in live_roots:
            spread(r, self._nodes[r].weight / root_w if root_w > 0 else 0.0)
        return out
