"""Gatekeeper: the auth proxy that mints the trusted identity header.

Rebuild of the reference's on-prem auth stack: the gatekeeper check
service (components/gatekeeper/auth/AuthServer.go:62-160 — basic-auth
password or session cookie, else redirect to the login page) plus the
kflogin flow (components/kflogin). Two roles:

- ``check(headers) -> user|None``: the ext_authz-style decision the
  reference exposes to Istio (ServeHTTP), usable in-process.
- ``AuthProxy``: an actual HTTP front door that terminates auth and
  forwards authenticated requests to an upstream L3 app with the trusted
  user-id header INJECTED (and any client-supplied copy stripped — the
  header is only trustworthy because nothing upstream accepts it from
  outside). This closes round-1's gap: "identity is a trusted header with
  nothing issuing/validating it" (VERDICT, missing #5).

Sessions are stateless HMAC tokens (user:expiry:sig) rather than the
reference's in-memory cookie table, so any replica can validate them.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import json
import secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from kubeflow_tpu.utils import get_logger

log = get_logger("gatekeeper")

COOKIE_NAME = "KFTPU-AUTH-KEY"
LOGIN_PATH = "/kflogin"
WHOAMI_PATH = "/whoami"
SESSION_TTL = 12 * 3600  # reference: 12h cookie expiry (AuthServer.go:185)

LOGIN_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Sign in</title>
<style>body{font-family:system-ui,sans-serif;display:flex;height:100vh;
align-items:center;justify-content:center}form{display:flex;
flex-direction:column;gap:.5rem;min-width:16rem}input,button{padding:.5rem}
#err{color:#b3261e;min-height:1.2em}</style></head>
<body><form id="f"><h1>Kubeflow TPU</h1>
<input id="u" placeholder="username" autocomplete="username">
<input id="p" type="password" placeholder="password"
 autocomplete="current-password">
<button>Sign in</button><div id="err"></div></form>
<script>
document.getElementById('f').onsubmit = async (e) => {
  e.preventDefault();
  const r = await fetch('/kflogin', {method: 'POST',
    headers: {'content-type': 'application/json'},
    body: JSON.stringify({username: document.getElementById('u').value,
                          password: document.getElementById('p').value})});
  if (r.ok) { location.href = '/'; return; }
  const d = await r.json().catch(() => ({}));
  document.getElementById('err').textContent = d.error || r.statusText;
};
</script></body></html>"""


class SessionSigner:
    def __init__(self, secret: Optional[bytes] = None,
                 ttl_seconds: float = SESSION_TTL):
        self.secret = secret or secrets.token_bytes(32)
        self.ttl = ttl_seconds

    def issue(self, user: str, now: Optional[float] = None) -> str:
        expiry = int((now or time.time()) + self.ttl)
        payload = f"{user}:{expiry}"
        sig = hmac.new(self.secret, payload.encode(),
                       hashlib.sha256).hexdigest()
        token = f"{payload}:{sig}"
        return base64.urlsafe_b64encode(token.encode()).decode()

    def validate(self, token: str, now: Optional[float] = None) -> Optional[str]:
        try:
            raw = base64.urlsafe_b64decode(token.encode()).decode()
            user, expiry, sig = raw.rsplit(":", 2)
        except Exception:
            return None
        payload = f"{user}:{expiry}"
        want = hmac.new(self.secret, payload.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(sig, want):
            return None
        if (now or time.time()) > int(expiry):
            return None
        return user


class Gatekeeper:
    """Credential + session validation (the check service)."""

    def __init__(
        self,
        users: Dict[str, str],          # username -> password
        *,
        signer: Optional[SessionSigner] = None,
        user_domain: str = "",
    ):
        # Store only salted digests; constant-time compare on check.
        self._pwhash = {
            u: hashlib.sha256(p.encode()).digest() for u, p in users.items()
        }
        self.signer = signer or SessionSigner()
        self.user_domain = user_domain

    def identity(self, username: str) -> str:
        if self.user_domain and "@" not in username:
            return f"{username}@{self.user_domain}"
        return username

    def auth_password(self, username: str, password: str) -> Optional[str]:
        want = self._pwhash.get(username)
        got = hashlib.sha256(password.encode()).digest()
        # Always compare (timing) even for unknown users.
        ok = hmac.compare_digest(want or b"\0" * 32, got)
        if want is not None and ok:
            return self.identity(username)
        return None

    def auth_basic_header(self, header: str) -> Optional[str]:
        if not header.lower().startswith("basic "):
            return None
        try:
            raw = base64.b64decode(header[6:]).decode()
            username, _, password = raw.partition(":")
        except Exception:
            return None
        return self.auth_password(username, password)

    def check(self, headers: Dict[str, str]) -> Optional[str]:
        """ext_authz decision: returns the authenticated identity or None.
        Order mirrors AuthServer.ServeHTTP: cookie, then basic auth."""
        cookies = _parse_cookies(headers.get("cookie", ""))
        token = cookies.get(COOKIE_NAME)
        if token:
            user = self.signer.validate(token)
            if user:
                return user
        auth = headers.get("authorization", "")
        if auth:
            return self.auth_basic_header(auth)
        return None


class AuthProxy:
    """HTTP front door: login page endpoints + authenticated forwarding to
    one upstream app, injecting the trusted user-id header."""

    def __init__(
        self,
        gatekeeper: Gatekeeper,
        upstream_port: int,
        *,
        upstream_host: str = "127.0.0.1",
        user_id_header: str = "x-goog-authenticated-user-email",
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        gk = gatekeeper
        hdr = user_id_header

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, status: int, payload, extra_headers=(),
                      content_type: str = "application/json"):
                data = (json.dumps(payload).encode()
                        if not isinstance(payload, bytes) else payload)
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", "0") or 0)
                if not n:
                    return {}
                try:
                    return json.loads(self.rfile.read(n))
                except json.JSONDecodeError:
                    return {}

            def _handle(self, method: str) -> None:
                if self.path.startswith(WHOAMI_PATH):
                    user = gk.check({k.lower(): v
                                     for k, v in self.headers.items()})
                    self._send(200, {"user": user or ""})
                    return
                if self.path.startswith(LOGIN_PATH):
                    self._login(method)
                    return
                user = gk.check({k.lower(): v
                                 for k, v in self.headers.items()})
                if user is None:
                    # Browser flow: redirect to login (AuthServer.go:162);
                    # API flow gets the 302 too and can follow with creds.
                    self._send(
                        302, {"error": "authentication required"},
                        extra_headers=[("Location", LOGIN_PATH)],
                    )
                    return
                self._forward(method, user)

            def _login(self, method: str) -> None:
                if method != "POST":
                    # Browsers get the login page (the kflogin React app's
                    # equivalent, components/kflogin/src/login.js); API
                    # clients keep the JSON usage hint.
                    if "text/html" in self.headers.get("Accept", ""):
                        self._send(200, LOGIN_PAGE.encode(),
                                   content_type="text/html; charset=utf-8")
                        return
                    self._send(200, {"login": "POST {username, password}"})
                    return
                body = self._body()
                user = gk.auth_password(body.get("username", ""),
                                        body.get("password", ""))
                if user is None:
                    self._send(401, {"error": "invalid credentials"})
                    return
                token = gk.signer.issue(user)
                self._send(
                    205, {"user": user},
                    extra_headers=[(
                        "Set-Cookie",
                        f"{COOKIE_NAME}={token}; Path=/; HttpOnly; "
                        "SameSite=Strict",
                    )],
                )

            def _forward(self, method: str, user: str) -> None:
                n = int(self.headers.get("Content-Length", "0") or 0)
                body = self.rfile.read(n) if n else None
                conn = http.client.HTTPConnection(
                    upstream_host, upstream_port, timeout=10
                )
                fwd_headers = {
                    k: v for k, v in self.headers.items()
                    # Strip client-supplied identity + hop headers.
                    if k.lower() not in (hdr, "host", "content-length",
                                         "connection")
                }
                fwd_headers[hdr] = user
                if body is not None:
                    fwd_headers["Content-Length"] = str(len(body))
                try:
                    conn.request(method, self.path, body=body,
                                 headers=fwd_headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    self._send(resp.status, data)
                except OSError as e:
                    self._send(502, {"error": f"upstream unreachable: {e}"})
                finally:
                    conn.close()

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_DELETE(self):
                self._handle("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "AuthProxy":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def _parse_cookies(header: str) -> Dict[str, str]:
    out = {}
    for part in header.split(";"):
        name, _, value = part.strip().partition("=")
        if name:
            out[name] = value
    return out


def main(argv=None) -> int:
    """Gatekeeper pod/sidecar entrypoint: authenticate, then forward to the
    upstream app with the trusted identity header injected. Credentials
    come from a mounted secret file of ``username:password`` lines
    (--users-file) — the reference's flag/secret pair (AuthServer.go)."""
    import argparse

    from kubeflow_tpu.controlplane.runtime.backend import serve_forever

    p = argparse.ArgumentParser(prog="kftpu-gatekeeper")
    p.add_argument("--users-file", required=True)
    p.add_argument("--session-secret-file", default="",
                   help="HMAC key for session cookies; REQUIRED for "
                        "multi-replica or restart-surviving sessions "
                        "(without it each process mints a random key and "
                        "other replicas/restarts reject its cookies)")
    p.add_argument("--upstream-host", default="127.0.0.1")
    p.add_argument("--upstream-port", type=int, required=True)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--user-domain", default="")
    p.add_argument("--user-id-header",
                   default="x-goog-authenticated-user-email")
    args = p.parse_args(argv)

    users = {}
    with open(args.users_file) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or ":" not in line:
                continue
            u, pw = line.split(":", 1)
            users[u] = pw
    if not users:
        raise SystemExit(f"no credentials in {args.users_file!r}")
    placeholders = [u for u, pw in users.items() if pw == "changeme"]
    if placeholders:
        # The shipped manifests carry a must-change bootstrap secret;
        # refusing to serve with it beats running an "authenticated"
        # platform whose password is public.
        raise SystemExit(
            f"placeholder password for {placeholders!r} in "
            f"{args.users_file!r} — change it before starting the gatekeeper"
        )
    signer = None
    if args.session_secret_file:
        with open(args.session_secret_file, "rb") as f:
            signer = SessionSigner(secret=f.read().strip())
    else:
        log.warning(
            "no --session-secret-file: session cookies will not survive "
            "restarts and cannot be shared across replicas"
        )
    gk = Gatekeeper(users, user_domain=args.user_domain, signer=signer)
    proxy = AuthProxy(
        gk, args.upstream_port, upstream_host=args.upstream_host,
        user_id_header=args.user_id_header, host=args.host, port=args.port,
    )
    proxy.start()
    log.info("gatekeeper up", kv={"port": proxy.port,
                                  "upstream": args.upstream_port,
                                  "users": len(users)})
    serve_forever(proxy.stop)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
