"""Minimal JSON-over-HTTP router for the L3 apps (stdlib only).

Replaces Flask's Blueprint routing (reference jupyter-web-app
base_app.py:22-175) and Express's Router (centraldashboard
api_workgroup.ts:247) with one shared dispatcher: route patterns with
``<name>`` path params, a trusted identity header (populated by the
gatekeeper auth proxy / IAP, reference gatekeeper/auth/AuthServer.go:62),
and JSON bodies both ways.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from kubeflow_tpu.utils import get_logger

log = get_logger("webapps")


class RestError(Exception):
    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        # Extra response headers (e.g. Retry-After on a 503 so clients
        # back off instead of hammering a backendless balancer).
        self.headers = dict(headers or {})


class Html(str):
    """Handler return type for text/html responses (the minimal frontend
    pages); everything else stays JSON."""


class NdjsonStream:
    """Handler return type for streaming responses: an iterator of
    JSON-able payloads written as newline-delimited JSON with chunked
    transfer encoding (the serving front-end's token streaming)."""

    def __init__(self, chunks):
        self.chunks = chunks


@dataclasses.dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str]          # path params from <name> segments
    query: Dict[str, str]
    body: Dict[str, Any]
    caller: str                     # trusted identity header value ("" = anon)
    headers: Dict[str, str]


Handler = Callable[[Request], Any]


def _compile(pattern: str) -> re.Pattern:
    regex = re.sub(r"<([a-zA-Z_][a-zA-Z0-9_]*)>", r"(?P<\1>[^/]+)", pattern)
    return re.compile(f"^{regex}$")


class Router:
    """Method+pattern table. Handlers return a JSON-able payload (status
    200) or a (status, payload) tuple; raise RestError for error codes."""

    def __init__(self) -> None:
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.append((method.upper(), _compile(pattern), handler))

    def include(self, other: "Router") -> None:
        """Mount another router's routes (earlier routes win)."""
        self._routes.extend(other._routes)

    def get(self, pattern: str, handler: Handler) -> None:
        self.add("GET", pattern, handler)

    def post(self, pattern: str, handler: Handler) -> None:
        self.add("POST", pattern, handler)

    def delete(self, pattern: str, handler: Handler) -> None:
        self.add("DELETE", pattern, handler)

    def dispatch(self, req: Request) -> Tuple[int, Any]:
        matched_path = False
        for method, pattern, handler in self._routes:
            m = pattern.match(req.path)
            if m is None:
                continue
            matched_path = True
            if method != req.method:
                continue
            # Percent-decode AFTER segment matching (a %2F in a resource
            # name must not smuggle a path separator past the route
            # pattern) — the same order Flask/werkzeug uses. Found by the
            # executed-page-JS tier: encodeURIComponent'd names arrived
            # still encoded and lookups missed.
            req.params = {k: unquote(v) for k, v in m.groupdict().items()}
            out = handler(req)
            if isinstance(out, tuple):
                return out
            return 200, out
        if matched_path:
            return 405, {"error": f"method {req.method} not allowed"}
        return 404, {"error": f"no route for {req.path}"}


class JsonHttpServer:
    """ThreadingHTTPServer wrapper shared by JWA/dashboard (same lifecycle
    as controlplane.kfam.KfamHttpServer)."""

    def __init__(
        self,
        router: Router,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        user_id_header: str = "x-goog-authenticated-user-email",
    ):
        self.router = router
        rt = router
        hdr = user_id_header

        class _Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: chunked Transfer-Encoding (NdjsonStream) is not
            # defined for the default HTTP/1.0; every non-stream response
            # sends Content-Length, so keep-alive semantics stay correct.
            protocol_version = "HTTP/1.1"
            # Bound idle keep-alive connections: without a timeout each
            # persistent connection pins a ThreadingHTTPServer thread
            # forever in readline() (HTTP/1.0 used to close per response).
            timeout = 65

            def log_message(self, *a):
                pass

            def _serve(self, method: str) -> None:
                if "chunked" in (
                    self.headers.get("Transfer-Encoding") or ""
                ).lower():
                    # Body parsing is Content-Length-only; silently reading
                    # an empty body would leave chunk framing on the wire
                    # and desync the keep-alive connection.
                    self._send(411, {
                        "error": "chunked request bodies unsupported; "
                                 "send Content-Length"
                    })
                    self.close_connection = True
                    return
                self._serve_inner(method)

            def _serve_inner(self, method: str) -> None:
                url = urlparse(self.path)
                n = int(self.headers.get("Content-Length", "0") or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    body = json.loads(raw) if raw else {}
                except json.JSONDecodeError as e:
                    self._send(400, {"error": f"bad JSON body: {e}"})
                    return
                req = Request(
                    method=method,
                    path=url.path,
                    params={},
                    query={k: v[0] for k, v in parse_qs(url.query).items()},
                    body=body if isinstance(body, dict) else {"_": body},
                    caller=self.headers.get(hdr, ""),
                    headers={k.lower(): v for k, v in self.headers.items()},
                )
                extra_headers: Dict[str, str] = {}
                try:
                    status, payload = rt.dispatch(req)
                except RestError as e:
                    status, payload = e.status, {"error": str(e)}
                    extra_headers = e.headers
                except KeyError as e:
                    status, payload = 400, {"error": f"missing field {e}"}
                except Exception as e:  # surface, don't kill the thread
                    log.error("handler error", kv={"path": url.path,
                                                   "err": repr(e)})
                    status, payload = 500, {"error": "internal error"}
                self._send(status, payload, extra_headers)

            def _send(self, status: int, payload: Any,
                      extra_headers: Optional[Dict[str, str]] = None) -> None:
                if isinstance(payload, NdjsonStream):
                    # HTTP/1.0 clients cannot parse chunked transfer
                    # coding: stream to them close-delimited (raw NDJSON,
                    # end of body == connection close).
                    chunked = self.request_version != "HTTP/1.0"
                    self.send_response(status)
                    self.send_header("Content-Type", "application/x-ndjson")
                    if chunked:
                        self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    try:
                        for chunk in payload.chunks:
                            data = (json.dumps(chunk) + "\n").encode()
                            if chunked:
                                data = (
                                    f"{len(data):x}\r\n".encode() + data
                                    + b"\r\n"
                                )
                            self.wfile.write(data)
                            self.wfile.flush()
                        if chunked:
                            self.wfile.write(b"0\r\n\r\n")
                    except (BrokenPipeError, ConnectionResetError):
                        pass             # client went away mid-stream
                    except Exception as e:  # generator bug: end the
                        log.error("stream error",   # stream, keep thread
                                  kv={"err": repr(e)})
                    # The chunk framing may be incomplete on any error
                    # path above — never reuse this connection.
                    self.close_connection = True
                    return
                if isinstance(payload, Html):
                    ctype, data = "text/html; charset=utf-8", payload.encode()
                else:
                    ctype, data = "application/json", json.dumps(payload).encode()
                self.send_response(status)
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                if (300 <= status < 400 and isinstance(payload, dict)
                        and "location" in payload):
                    self.send_header("Location", payload["location"])
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

            def do_DELETE(self):
                self._serve("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "JsonHttpServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() handshakes with serve_forever — calling it on a
        # never-started server waits forever on the is_shut_down event.
        if self._thread is not None:
            self.httpd.shutdown()
        # Release the listening socket: without server_close() the port
        # keeps accepting connections into the backlog after stop(), so a
        # "dead" server looks alive to health checks and failover logic.
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
