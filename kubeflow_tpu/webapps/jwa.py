"""Notebook web app backend (JWA): spawner REST over the Notebook CRD.

Rebuild of the reference jupyter-web-app backend
(kubeflow_jupyter/common/base_app.py:22-175 routes, default/app.py:13-73
POST form -> Notebook CR), with every request authorized by a
SubjectAccessReview for the trusted user-id header
(common/auth.py:21-60 ``needs_authorization``).

TPU twist: the spawner's GPU vendor/limit pickers
(common/utils.py:390-443) become a typed TPU slice picker driven by the
topology catalogue; "configurations" are PodDefault labels, as upstream.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

from kubeflow_tpu.controlplane.api.meta import ObjectMeta
from kubeflow_tpu.controlplane.api.types import Notebook, NotebookSpec
from kubeflow_tpu.controlplane.kfam.authz import SubjectAccessReviewer
from kubeflow_tpu.controlplane.runtime.apiserver import (
    AlreadyExistsError,
    InMemoryApiServer,
    NotFoundError,
)
from kubeflow_tpu.topology import get_slice, list_slices
from kubeflow_tpu.utils.monitoring import MetricsRegistry, global_registry
from kubeflow_tpu.webapps.router import JsonHttpServer, Request, RestError, Router

_DNS1123 = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")

DEFAULT_IMAGES = (
    "kubeflow-tpu/jupyter:latest",
    "kubeflow-tpu/jupyter-jax:latest",
    "kubeflow-tpu/jupyter-pytorch-xla:latest",
)

# Single-host slices a notebook can attach (multi-host attachment is a
# TpuJob concern, not an interactive-pod one).
def _notebook_slices() -> List[str]:
    return [s for s in list_slices() if get_slice(s).num_hosts == 1]


class NotebookWebApp:
    """In-process operations + route table. Serve with ``serve()``."""

    def __init__(
        self,
        api: InMemoryApiServer,
        registry: MetricsRegistry = global_registry,
        *,
        user_id_header: str = "x-goog-authenticated-user-email",
        images: tuple = DEFAULT_IMAGES,
    ):
        self.api = api
        self.sar = SubjectAccessReviewer(api)
        self.user_id_header = user_id_header
        self.images = list(images)
        self.requests = registry.counter(
            "kftpu_jwa_requests_total", "JWA ops", ("op", "result")
        )
        self.heartbeat = registry.heartbeat("jupyter-web-app")

    # ---------------- authz (reference auth.py:21-60) ----------------

    def _authorize(self, caller: str, verb: str, namespace: str) -> None:
        if not caller:
            raise RestError(401, "missing identity header")
        if self.sar.is_cluster_admin(caller):
            return
        if not self.sar.can(caller, verb, namespace):
            raise RestError(
                403,
                f"{caller} is not authorized to {verb} notebooks "
                f"in namespace {namespace}",
            )

    # ---------------- operations ----------------

    def spawner_config(self) -> Dict[str, Any]:
        return {
            "images": self.images,
            "defaultImage": self.images[0],
            "cpu": {"default": "2"},
            "memory": {"default": "4Gi"},
            "tpuSlices": _notebook_slices(),
        }

    def list_namespaces(self, caller: str) -> List[str]:
        if not caller:
            raise RestError(401, "missing identity header")
        out = []
        for ns in self.api.list("Namespace", copy=False):
            if self.sar.is_cluster_admin(caller) or self.sar.can(
                caller, "list", ns.metadata.name
            ):
                out.append(ns.metadata.name)
        return sorted(out)

    def list_notebooks(self, caller: str, namespace: str) -> List[Dict]:
        self._authorize(caller, "list", namespace)
        self.heartbeat.beat()
        items = []
        for nb in self.api.list("Notebook", namespace=namespace, copy=False):
            items.append(self._render(nb))
        self.requests.inc(op="list", result="ok")
        return items

    def create_notebook(self, caller: str, namespace: str,
                        form: Dict[str, Any]) -> Dict:
        self._authorize(caller, "create", namespace)
        self.heartbeat.beat()
        name = form.get("name", "")
        if not name:
            raise RestError(400, "notebook name required")
        if not _DNS1123.match(name):
            # K8s object-name rules; also keeps stored markup out of every
            # UI that renders names.
            raise RestError(
                400,
                f"invalid notebook name {name!r}: must be DNS-1123 "
                "(lowercase alphanumerics and '-', max 63 chars)",
            )
        tpu_slice = form.get("tpuSlice", "")
        if tpu_slice:
            try:
                s = get_slice(tpu_slice)
            except KeyError:
                raise RestError(400, f"unknown TPU slice type {tpu_slice!r}")
            if s.num_hosts != 1:
                raise RestError(
                    400,
                    f"slice {tpu_slice} spans {s.num_hosts} hosts; notebooks "
                    "attach single-host slices only (use a TpuJob)",
                )
        checkpoint = form.get("checkpoint", "")
        if checkpoint:
            from kubeflow_tpu.controlplane.ckpt_catalog import (
                resolve_checkpoint,
            )

            if resolve_checkpoint(self.api, namespace, checkpoint) is None:
                raise RestError(
                    400,
                    f"unknown checkpoint {checkpoint!r}: no TpuJob in "
                    f"{namespace} with a completed checkpoint step by "
                    "that name (GET .../checkpoints lists them)",
                )
        nb = Notebook(
            metadata=ObjectMeta(
                name=name,
                namespace=namespace,
                labels={"app.kubernetes.io/created-by": "jupyter-web-app"},
                annotations={"owner": caller},
            ),
            spec=NotebookSpec(
                image=form.get("image", self.images[0]),
                cpu=str(form.get("cpu", "2")),
                memory=str(form.get("memory", "4Gi")),
                tpu_slice=tpu_slice,
                pod_defaults=list(form.get("configurations", [])),
                checkpoint=checkpoint,
            ),
        )
        try:
            self.api.create(nb)
        except AlreadyExistsError:
            self.requests.inc(op="create", result="conflict")
            raise RestError(409, f"notebook {namespace}/{name} exists")
        self.requests.inc(op="create", result="ok")
        return self._render(nb)

    def delete_notebook(self, caller: str, namespace: str, name: str) -> None:
        self._authorize(caller, "delete", namespace)
        self.heartbeat.beat()
        try:
            self.api.delete("Notebook", name, namespace)
        except NotFoundError:
            self.requests.inc(op="delete", result="missing")
            raise RestError(404, f"notebook {namespace}/{name} not found")
        self.requests.inc(op="delete", result="ok")

    def list_checkpoints(self, caller: str, namespace: str) -> List[Dict]:
        """Spawnable checkpoints (the Rok variant's snapshot listing,
        rok/app.py:16-136): TpuJob-produced orbax checkpoints with at
        least one completed step."""
        self._authorize(caller, "list", namespace)
        self.heartbeat.beat()
        from kubeflow_tpu.controlplane.ckpt_catalog import list_checkpoints

        return list_checkpoints(self.api, namespace)

    def list_poddefaults(self, caller: str, namespace: str) -> List[Dict]:
        self._authorize(caller, "list", namespace)
        out = []
        for pd in self.api.list("PodDefault", namespace=namespace,
                                copy=False):
            labels = list(pd.spec.selector.keys())
            out.append({
                "label": labels[0] if labels else pd.metadata.name,
                "desc": pd.spec.desc or pd.metadata.name,
            })
        return out

    # ---------------- rendering (utils.process_resource analogue) -------

    def _render(self, nb: Notebook) -> Dict[str, Any]:
        # Status derivation: mirror the reference's event/condition folding
        # (common/utils.py:262-335) from our controller's conditions.
        phase, reason = "waiting", "Scheduling the notebook pod"
        for c in nb.status.conditions:
            if c.type == "Ready":
                if c.status == "True":
                    phase, reason = "running", "Notebook is ready"
                else:
                    phase, reason = "waiting", c.message or c.reason
        if nb.metadata.annotations.get("kubeflow-resource-stopped"):
            phase, reason = "stopped", "Notebook is culled/stopped"
        events = [
            {"reason": e.reason, "message": e.message, "type": e.type}
            for e in self.api.list("Event", namespace=nb.metadata.namespace,
                                    copy=False)
            if e.involved_kind == "Notebook"
            and e.involved_name == nb.metadata.name
        ]
        return {
            "name": nb.metadata.name,
            "namespace": nb.metadata.namespace,
            "image": nb.spec.image,
            "cpu": nb.spec.cpu,
            "memory": nb.spec.memory,
            "tpuSlice": nb.spec.tpu_slice,
            "configurations": list(nb.spec.pod_defaults),
            "checkpoint": nb.spec.checkpoint,
            "owner": nb.metadata.annotations.get("owner", ""),
            "status": {"phase": phase, "reason": reason},
            "events": events,
        }

    # ---------------- HTTP ----------------

    def router(self) -> Router:
        r = Router()
        r.get("/api/config",
              lambda q: {"success": True, "config": self.spawner_config()})
        r.get("/api/namespaces",
              lambda q: {"success": True,
                         "namespaces": self.list_namespaces(q.caller)})
        r.get(
            "/api/namespaces/<ns>/notebooks",
            lambda q: {"success": True,
                       "notebooks": self.list_notebooks(
                           q.caller, q.params["ns"])},
        )
        r.post(
            "/api/namespaces/<ns>/notebooks",
            lambda q: {"success": True,
                       "notebook": self.create_notebook(
                           q.caller, q.params["ns"], q.body)},
        )
        r.get(
            "/api/namespaces/<ns>/checkpoints",
            lambda q: {"success": True,
                       "checkpoints": self.list_checkpoints(
                           q.caller, q.params["ns"])},
        )
        r.delete(
            "/api/namespaces/<ns>/notebooks/<nb>",
            lambda q: (self.delete_notebook(q.caller, q.params["ns"],
                                            q.params["nb"]),
                       {"success": True})[1],
        )
        r.get(
            "/api/namespaces/<ns>/poddefaults",
            lambda q: {"success": True,
                       "poddefaults": self.list_poddefaults(
                           q.caller, q.params["ns"])},
        )
        r.get("/healthz/liveness", lambda q: "alive")
        r.get("/healthz/readiness", lambda q: "ready")
        return r

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> JsonHttpServer:
        return JsonHttpServer(
            self.router(), host=host, port=port,
            user_id_header=self.user_id_header,
        ).start()
