"""MicroBrowser — a headless page harness that EXECUTES the served UI.

The reference drives its frontends with real browsers (Selenium over
jupyter-web-app — testing/test_jwa.py:32-423 — and puppeteer over
centraldashboard — components/centraldashboard/test/e2e.test.ts). This
image has no browser and no JS runtime, so the framework ships the whole
stack itself: ``minijs`` interprets the page script, and this module is
the browser around it — document/elements, (synchronous) fetch against
the live HTTP server with the trusted identity header injected (standing
in for the gatekeeper AuthProxy), and enough form/select semantics for
the pages' flows.

What is faithfully modeled (because the pages use it):

- ``document.getElementById`` with an auto-creating element registry;
  elements carry ``innerHTML``/``value``/``textContent`` and writable
  ``onsubmit``/``onclick``/``onchange`` handler slots
- setting ``innerHTML`` containing ``<option>`` rows updates ``value`` to
  the first option (browser select behavior the scripts rely on)
- ``element.querySelectorAll('button.del')`` parses the element's
  rendered HTML and returns stable button objects (handler assignments
  from the page's event-delegation pass stay addressable by the test)
- ``document.querySelectorAll('input.comp:checked')`` over the static
  page HTML (the click-to-deploy component checkboxes)
- ``fetch(path, opts)``: urllib against ``base_url`` with the identity
  header; a Response exposes ``ok``/``status``/``statusText``/``json()``

Async collapses to synchronous execution (see minijs), so after
``submit()``/``click()`` return, every await in the handler chain —
including the refresh re-render — has completed: no settling sleeps.
"""

from __future__ import annotations

import html as _html_mod
import json as _json
import re
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from kubeflow_tpu.webapps.minijs import Interpreter, undefined

__all__ = ["MicroBrowser", "Element"]

_OPTION_RE = re.compile(
    r"<option(?P<attrs>[^>]*)>(?P<text>[^<]*)", re.I)
_VALUE_ATTR_RE = re.compile(r'value="(?P<v>[^"]*)"')
_DEL_BTN_RE = re.compile(r'<button class="del" data-name="(?P<name>[^"]*)"')
_CHECKBOX_RE = re.compile(
    r'<input type="checkbox" class="comp" value="(?P<v>[^"]*)"'
    r"(?P<checked> checked)?", re.I)
_SCRIPT_RE = re.compile(r"<script>(.*?)</script>", re.S)


def _unescape(s: str) -> str:
    return _html_mod.unescape(s)


class _DelButton:
    """A delegation-target button: the page assigns ``onclick`` on it."""

    def __init__(self, name: str):
        self.dataset = {"name": name}
        self.onclick: Optional[Callable] = None


class _Checkbox:
    def __init__(self, value: str, checked: bool):
        self.value = value
        self.checked = checked


class Element:
    """Just enough DOM element: handler slots are ordinary attributes
    (minijs host-object setattr), innerHTML tracks select semantics."""

    def __init__(self, el_id: str):
        self.id = el_id
        self._html = ""
        self.value = ""
        self.textContent = ""
        self.onsubmit: Optional[Callable] = None
        self.onclick: Optional[Callable] = None
        self.onchange: Optional[Callable] = None
        self._del_buttons: List[_DelButton] = []

    # innerHTML is a property so select-value and delegation-button
    # bookkeeping stay in sync with what the page renders.
    @property
    def innerHTML(self) -> str:  # noqa: N802 — DOM casing
        return self._html

    @innerHTML.setter
    def innerHTML(self, v) -> None:  # noqa: N802
        self._html = str(v)
        self._del_buttons = []
        m = _OPTION_RE.search(self._html)
        if m is not None:
            # Browser behavior: assigning options selects the first one.
            va = _VALUE_ATTR_RE.search(m.group("attrs") or "")
            self.value = _unescape(
                va.group("v") if va is not None else m.group("text"))

    def querySelectorAll(self, selector):  # noqa: N802 — DOM casing
        if selector == "button.del":
            if not self._del_buttons:
                self._del_buttons = [
                    _DelButton(_unescape(m.group("name")))
                    for m in _DEL_BTN_RE.finditer(self._html)
                ]
            return list(self._del_buttons)
        return []

    def del_button(self, name: str) -> _DelButton:
        """Test accessor: the button object the page's delegation pass
        assigned ``onclick`` on (same identity, not a re-parse)."""
        for b in self._del_buttons or self.querySelectorAll("button.del"):
            if b.dataset["name"] == name:
                return b
        raise AssertionError(
            f"no delete button for {name!r} in #{self.id}: {self._html!r}")


class _Document:
    def __init__(self, page_html: str):
        self._elements: Dict[str, Element] = {}
        self._page_html = page_html
        self._checkboxes = [
            _Checkbox(_unescape(m.group("v")), bool(m.group("checked")))
            for m in _CHECKBOX_RE.finditer(page_html)
        ]

    def getElementById(self, el_id):  # noqa: N802 — DOM casing
        el_id = str(el_id)
        if el_id not in self._elements:
            self._elements[el_id] = Element(el_id)
        return self._elements[el_id]

    def querySelectorAll(self, selector):  # noqa: N802 — DOM casing
        if selector == "input.comp:checked":
            return [c for c in self._checkboxes if c.checked]
        return []


class _Response:
    def __init__(self, status: int, body: bytes, reason: str = ""):
        self.status = float(status)
        self.ok = 200 <= status < 300
        self.statusText = reason or str(status)
        self._body = body

    def json(self):
        try:
            return _json.loads(self._body.decode() or "null")
        except ValueError:
            return {"error": self._body.decode(errors="replace")[:200]}


class _Location:
    def __init__(self):
        self.reloaded = 0

    def reload(self):
        self.reloaded += 1


class Event:
    """The event object handlers receive: only preventDefault is used."""

    def __init__(self):
        self.default_prevented = False

    def preventDefault(self):  # noqa: N802 — DOM casing
        self.default_prevented = True


class MicroBrowser:
    def __init__(self, base_url: str, *,
                 user_header: Optional[str] = None,
                 user: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.user_header = user_header
        self.user = user
        self.document: Optional[_Document] = None
        self.location = _Location()
        self.interp: Optional[Interpreter] = None
        self.page_html = ""

    # ---------------- network ----------------

    def fetch(self, path, opts=undefined):
        opts = opts if isinstance(opts, dict) else {}
        method = str(opts.get("method", "GET"))
        headers = dict(opts.get("headers") or {})
        if self.user_header and self.user:
            headers[self.user_header] = self.user
        body = opts.get("body")
        data = str(body).encode() if isinstance(body, str) else None
        url = path if str(path).startswith("http") else \
            self.base_url + str(path)
        req = urllib.request.Request(
            url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return _Response(r.status, r.read(), r.reason or "")
        except urllib.error.HTTPError as e:
            return _Response(e.code, e.read(), e.reason or "")

    # ---------------- page lifecycle ----------------

    def open(self, path: str) -> "MicroBrowser":
        """GET the page, build the document, EXECUTE its inline scripts.
        Returns self; on return the page's init flow (and every await in
        it) has completed."""
        r = self.fetch(path)
        if not r.ok:
            raise AssertionError(
                f"GET {path} -> {int(r.status)} {r.statusText}")
        self.page_html = r._body.decode()
        self.document = _Document(self.page_html)
        self.interp = Interpreter({
            "document": self.document,
            "location": self.location,
            "fetch": self.fetch,
            "setInterval": lambda fn, ms=0.0, *a: 0.0,
            "setTimeout": lambda fn, ms=0.0, *a: fn(),
            "clearInterval": lambda h=0.0: undefined,
            "window": {},
        })
        scripts = _SCRIPT_RE.findall(self.page_html)
        if not scripts:
            raise AssertionError(f"page {path} has no inline script")
        for script in scripts:
            self.interp.run(script)
        return self

    # ---------------- interaction ----------------

    def element(self, el_id: str) -> Element:
        assert self.document is not None, "open() a page first"
        return self.document.getElementById(el_id)

    def set_value(self, el_id: str, value: str) -> None:
        self.element(el_id).value = value

    def submit(self, form_id: str) -> Event:
        """Fire the form's submit handler exactly as the browser would.
        Raises minijs.JSError if the handler throws (e.g. an api() error
        the page chose not to catch)."""
        el = self.element(form_id)
        assert callable(el.onsubmit), f"#{form_id} has no submit handler"
        ev = Event()
        el.onsubmit(ev)
        return ev

    def click_delete(self, list_id: str, name: str) -> None:
        """Click the delegation-bound delete button for ``name``."""
        btn = self.element(list_id).del_button(name)
        assert callable(btn.onclick), \
            f"page never bound onclick for {name!r}"
        btn.onclick()

    def call(self, fn_name: str, *args) -> Any:
        """Invoke a page-script global (e.g. a manual refresh())."""
        assert self.interp is not None
        fn = self.interp.globals[fn_name]
        return fn(*args)
