"""Small platform web utilities: echo, https-redirect, static config.

The reference carries three single-purpose services this module rebuilds
on the shared Router:

- echo-server (components/echo-server — the IAP smoke-test app): reflects
  request identity/headers so auth-path tests can see what reached the
  backend through the gatekeeper/IAP hop.
- https-redirect (components/https-redirect): 301 every http request to
  the https origin.
- static-config-server (bootstrap static config serving): serve a config
  document at a fixed route; platform config UIs read it at startup.
"""

from __future__ import annotations

from typing import Any, Dict

from kubeflow_tpu.webapps.router import JsonHttpServer, Request, Router


def echo_app() -> Router:
    """Reflect the request: the IAP/gatekeeper smoke target. The caller
    field shows which trusted identity survived the proxy hop."""
    router = Router()

    def echo(req: Request) -> Any:
        return {
            "method": req.method,
            "path": req.path,
            "query": req.query,
            "caller": req.caller,
            "headers": {
                k: v for k, v in req.headers.items()
                if k.startswith("x-") or k in ("host", "user-agent")
            },
        }

    router.get("/.*", echo)
    router.post("/.*", echo)
    return router


def https_redirect_app(https_host: str = "") -> Router:
    """301 everything to https://<host><path> (components/https-redirect).
    With no explicit host, the request's Host header is reused."""
    router = Router()

    def redirect(req: Request):
        host = https_host or req.headers.get("host", "localhost")
        return 301, {"location": f"https://{host}{req.path}"}

    router.get("/.*", redirect)
    router.post("/.*", redirect)
    return router


def static_config_app(config: Dict[str, Any]) -> Router:
    """Serve one config document at /config (and /) — the static-config-
    server the deployment UIs poll."""
    router = Router()
    doc = dict(config)

    def get_config(req: Request) -> Any:
        return doc

    router.get("/config", get_config)
    router.get("/", get_config)
    return router


def serve(router: Router, *, host: str = "127.0.0.1",
          port: int = 0) -> JsonHttpServer:
    return JsonHttpServer(router, host=host, port=port).start()
