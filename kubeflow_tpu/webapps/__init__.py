"""L3 web/REST plane: the HTTP apps behind the dashboard.

The reference's L3 is a Flask backend per UI (jupyter-web-app,
base_app.py:22-175) plus an Express dashboard server with the workgroup
API (centraldashboard/app/server.ts:66-68, api_workgroup.ts:247-381). Here
each app is a thin stdlib-HTTP wrapper over in-process services — the same
split as kfam's AccessManagement / KfamHttpServer — so functional tests
drive the full login-header -> SAR -> CR flow over real HTTP without
Flask/Express.
"""

from kubeflow_tpu.webapps.router import JsonHttpServer, Request, RestError
from kubeflow_tpu.webapps.jwa import NotebookWebApp
from kubeflow_tpu.webapps.dashboard import DashboardApi

__all__ = [
    "JsonHttpServer",
    "Request",
    "RestError",
    "NotebookWebApp",
    "DashboardApi",
]
