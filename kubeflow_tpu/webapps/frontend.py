"""Central hub: the dashboard + spawner UI served from one process.

The reference ships ~12k lines of Polymer/Angular/React across
centraldashboard (public/components/*), jupyter-web-app frontend and
kflogin. A TPU-native rebuild does not need a JS build chain for the same
capability: these are dependency-free HTML/vanilla-JS pages rendered over
the SAME REST surface the reference frontends call —

- hub page "/" (dashboard-view + namespace-selector equivalents):
  workgroup env-info, namespace switcher, live tables of Notebooks /
  TpuJobs / Servings / StudyJobs with phases, contributor management
  (manage-users-view).
- "/spawner" (jupyter-web-app frontend): the spawn form driven by
  /api/config (images + TPU slice picker instead of GPU vendor limits),
  notebook list with connect/delete.

``central_hub`` mounts the pages, the workgroup API (DashboardApi), the
spawner API (NotebookWebApp) and a resources listing endpoint behind one
router, which a gatekeeper AuthProxy fronts in production.
"""

from __future__ import annotations

from typing import Any

from kubeflow_tpu.controlplane.kfam.authz import SubjectAccessReviewer
from kubeflow_tpu.webapps.router import (
    Html,
    JsonHttpServer,
    Request,
    RestError,
    Router,
)

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 nav a {{ margin-right: 1rem; }}
 table {{ border-collapse: collapse; margin: 1rem 0; min-width: 30rem; }}
 td, th {{ border: 1px solid #ccc; padding: .3rem .6rem; text-align: left; }}
 .phase-Running, .phase-Ready, .phase-Succeeded {{ color: #0a7d32; }}
 .phase-Failed {{ color: #b3261e; }}
 form * {{ margin: .2rem; }}
</style></head>
<body>
<nav><a href="/">Dashboard</a><a href="/spawner">Notebooks</a></nav>
<h1>{title}</h1>
{body}
<script>
const H = {{'content-type': 'application/json'}};
// All API data is escaped before hitting innerHTML: resource names are
// user-controlled (stored-XSS surface otherwise).
function esc(s) {{
  return String(s).replace(/[&<>"']/g, c => ({{'&': '&amp;', '<': '&lt;',
    '>': '&gt;', '"': '&quot;', "'": '&#39;'}})[c]);
}}
async function api(path, opts) {{
  const r = await fetch(path, opts);
  const data = await r.json();
  if (!r.ok) throw new Error(data.error || r.statusText);
  return data;
}}
function needsWorkgroup(el) {{
  el.innerHTML = '<p>No workgroup yet.</p>' +
    '<button id="mkwg">Create my workgroup</button>';
  document.getElementById('mkwg').onclick = async () => {{
    await api('/api/workgroup/create', {{method: 'POST', headers: H,
      body: JSON.stringify({{}})}});
    location.reload();
  }};
}}
{script}
</script></body></html>"""

_HUB_BODY = """
<div id="whoami"></div>
<label>Namespace: <select id="ns"></select></label>
<h2>Resources</h2><div id="resources"></div>
<h2>Contributors</h2><div id="contributors"></div>
<form id="addc"><input id="cemail" placeholder="user@example.com">
<button>Add contributor</button></form>
<h2>Cluster metrics</h2><div id="metrics"></div>
"""

_HUB_SCRIPT = """
async function loadNs() {
  const info = await api('/api/workgroup/env-info');
  document.getElementById('whoami').textContent = 'Signed in as ' + info.user;
  if (!info.namespaces.length) {
    needsWorkgroup(document.getElementById('resources'));
    return;
  }
  const sel = document.getElementById('ns');
  sel.innerHTML = info.namespaces.map(
    n => `<option value="${esc(n.namespace)}">${esc(n.namespace)}` +
         ` (${esc(n.role)})</option>`
  ).join('');
  sel.onchange = refresh; refresh();
}
async function refresh() {
  const ns = document.getElementById('ns').value;
  const res = await api(`/api/resources/${encodeURIComponent(ns)}`);
  document.getElementById('resources').innerHTML =
    Object.entries(res.resources).map(([kind, items]) =>
      `<h3>${esc(kind)}</h3><table><tr><th>name</th><th>phase</th></tr>` +
      items.map(i => `<tr><td>${esc(i.name)}</td>` +
        `<td class="phase-${esc(i.phase)}">${esc(i.phase)}</td></tr>`
      ).join('') + '</table>').join('');
  const c = await api(
    `/api/workgroup/get-contributors/${encodeURIComponent(ns)}`);
  document.getElementById('contributors').textContent =
    (Array.isArray(c) ? c : []).join(', ') || 'none';
}
document.getElementById('addc').onsubmit = async (e) => {
  e.preventDefault();
  const ns = document.getElementById('ns').value;
  await api(`/api/workgroup/add-contributor/${encodeURIComponent(ns)}`,
    {method: 'POST', headers: H, body: JSON.stringify(
      {contributor: document.getElementById('cemail').value})});
  refresh();
};
function spark(pts) {
  if (!pts.length) return '';
  const vals = pts.map(p => p.value);
  const lo = Math.min(...vals), hi = Math.max(...vals);
  const span = (hi - lo) || 1;
  const step = 120 / Math.max(1, pts.length - 1);
  const d = vals.map((v, i) =>
    `${(i * step).toFixed(1)},${(24 - 22 * (v - lo) / span).toFixed(1)}`
  ).join(' ');
  return `<svg width="120" height="26"><polyline points="${esc(d)}"` +
    ` fill="none" stroke="#1a73e8" stroke-width="1.5"/></svg>`;
}
async function loadMetrics() {
  // The time-series plane is optional (mounted when a MetricsService is
  // wired into the hub); a 404 just hides the panel.
  let names;
  try { names = (await api('/api/metrics')).series; }
  catch (e) { return; }
  const series = await Promise.all(names.slice(0, 12).map(n =>
    api(`/api/metrics/${encodeURIComponent(n)}?window=3600`)));
  const rows = [];
  for (const s of series) {
    // One row per label set so per-device / per-label streams never
    // interleave into a single misleading line.
    const groups = (s.groups && s.groups.length)
      ? s.groups : [{labels: {}, points: s.points}];
    for (const g of groups) {
      if (!g.points.length) continue;
      const lbl = Object.entries(g.labels || {})
        .map(([k, v]) => k + '=' + v).join(',');
      const name = lbl ? s.series + '{' + lbl + '}' : s.series;
      const last = g.points[g.points.length - 1].value;
      rows.push(`<tr><td>${esc(name)}</td>` +
        `<td>${esc(Number(last).toPrecision(4))}</td>` +
        `<td>${spark(g.points)}</td></tr>`);
    }
  }
  if (rows.length)
    document.getElementById('metrics').innerHTML =
      '<table><tr><th>series</th><th>latest</th><th>last hour</th></tr>' +
      rows.join('') + '</table>';
}
loadNs(); loadMetrics(); setInterval(loadMetrics, 30000);
"""

_SPAWNER_BODY = """
<form id="spawn">
 <input id="name" placeholder="notebook name" required>
 <select id="image"></select>
 <select id="slice"></select>
 <select id="ckpt"></select>
 <button>Spawn</button>
</form>
<h2>Notebooks</h2><div id="list"></div>
"""

_SPAWNER_SCRIPT = """
let NS = '';
async function init() {
  const info = await api('/api/workgroup/env-info');
  if (!info.namespaces.length) {
    needsWorkgroup(document.getElementById('list'));
    return;
  }
  NS = info.namespaces[0].namespace;
  const cfg = (await api('/api/config')).config;
  document.getElementById('image').innerHTML =
    cfg.images.map(i => `<option>${esc(i)}</option>`).join('');
  document.getElementById('slice').innerHTML =
    '<option value="">no TPU</option>' +
    cfg.tpuSlices.map(s => `<option>${esc(s)}</option>`).join('');
  // Spawn-from-checkpoint picker (Rok-variant snapshot list): every
  // TpuJob-produced orbax checkpoint in the namespace.
  const ck = await api(`/api/namespaces/${encodeURIComponent(NS)}/checkpoints`);
  document.getElementById('ckpt').innerHTML =
    '<option value="">blank notebook</option>' +
    ck.checkpoints.map(c =>
      `<option value="${esc(c.name)}">from ${esc(c.name)}` +
      ` @ step ${esc(c.latestStep)}</option>`).join('');
  refresh();
}
async function refresh() {
  const out = await api(
    `/api/namespaces/${encodeURIComponent(NS)}/notebooks`);
  const list = document.getElementById('list');
  list.innerHTML =
    '<table><tr><th>name</th><th>image</th><th>status</th><th></th></tr>' +
    out.notebooks.map(n =>
      `<tr><td><a href="/notebook/${encodeURIComponent(NS)}/` +
      `${encodeURIComponent(n.name)}/">${esc(n.name)}</a></td>` +
      `<td>${esc(n.image)}</td>` +
      `<td class="phase-${esc(n.status.phase)}">${esc(n.status.phase)}` +
      `</td><td><button class="del" data-name="${esc(n.name)}">delete` +
      `</button></td></tr>`).join('') + '</table>';
  // Event delegation, no inline JS-string interpolation (XSS).
  list.querySelectorAll('button.del').forEach(b => b.onclick = async () => {
    await api(`/api/namespaces/${encodeURIComponent(NS)}/notebooks/` +
      encodeURIComponent(b.dataset.name), {method: 'DELETE'});
    refresh();
  });
}
document.getElementById('spawn').onsubmit = async (e) => {
  e.preventDefault();
  await api(`/api/namespaces/${encodeURIComponent(NS)}/notebooks`,
    {method: 'POST', headers: H, body: JSON.stringify({
      name: document.getElementById('name').value,
      image: document.getElementById('image').value,
      tpuSlice: document.getElementById('slice').value,
      checkpoint: document.getElementById('ckpt').value,
    })});
  refresh();
};
init();
"""


def central_hub(api, dashboard, jwa, metrics_service=None) -> Router:
    """One router serving pages + the dashboard/spawner REST surface (+ the
    time-series metrics API when a MetricsService is wired in)."""
    r = Router()
    r.get("/", lambda q: Html(_PAGE.format(
        title="Kubeflow TPU", body=_HUB_BODY, script=_HUB_SCRIPT)))
    r.get("/spawner", lambda q: Html(_PAGE.format(
        title="Notebook Spawner", body=_SPAWNER_BODY,
        script=_SPAWNER_SCRIPT)))

    sar = SubjectAccessReviewer(api)

    def resources(q: Request) -> Any:
        ns = q.params["ns"]
        if not q.caller:
            raise RestError(401, "identity header required")
        if not (sar.is_cluster_admin(q.caller)
                or sar.can(q.caller, "list", ns)):
            raise RestError(403, f"{q.caller} cannot list in {ns}")
        out = {}
        for kind in ("Notebook", "TpuJob", "Serving", "StudyJob"):
            items = []
            for o in api.list(kind, namespace=ns, copy=False):
                st = getattr(o, "status", None)
                phase = (getattr(st, "phase", "")
                         or getattr(st, "condition", "")
                         or getattr(st, "container_state", "")) or "Unknown"
                items.append({"name": o.metadata.name, "phase": phase})
            out[kind] = items
        return {"resources": out}

    r.get("/api/resources/<ns>", resources)
    r.include(dashboard.router())
    r.include(jwa.router())
    if metrics_service is not None:
        r.include(metrics_service.router())
    return r


def serve_hub(api, dashboard, jwa, *, host: str = "127.0.0.1",
              port: int = 0, user_id_header: str,
              metrics_service=None) -> JsonHttpServer:
    return JsonHttpServer(
        central_hub(api, dashboard, jwa, metrics_service),
        host=host, port=port,
        user_id_header=user_id_header,
    ).start()


def main(argv=None) -> int:
    """Hub pod entrypoint: pages + workgroup + spawner APIs against a
    cluster backend. The trusted identity header is only trustworthy when
    a gatekeeper AuthProxy fronts this server (the emitted K8s manifests
    run one as a sidecar; the hub itself binds localhost there)."""
    import argparse

    from kubeflow_tpu.controlplane.kfam import AccessManagement
    from kubeflow_tpu.controlplane.runtime.backend import (
        add_backend_args,
        build_backend,
        serve_forever,
    )
    from kubeflow_tpu.utils.monitoring import MetricsRegistry
    from kubeflow_tpu.webapps.dashboard import DashboardApi
    from kubeflow_tpu.webapps.jwa import NotebookWebApp

    p = argparse.ArgumentParser(prog="kftpu-hub")
    add_backend_args(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8082)
    p.add_argument("--metrics-port", type=int, default=9091,
                   help="-1 disables the metrics endpoint")
    p.add_argument("--user-id-header",
                   default="x-goog-authenticated-user-email")
    args = p.parse_args(argv)

    api = build_backend(args)
    registry = MetricsRegistry()
    am = AccessManagement(api, registry,
                          user_id_header=args.user_id_header)
    jwa = NotebookWebApp(api, registry, user_id_header=args.user_id_header)
    dashboard = DashboardApi(am)
    # Time-series plane: sample host/TPU/registry metrics into the store
    # the /api/metrics routes read (reference MetricsService).
    from kubeflow_tpu.webapps.metrics import (
        MetricsCollector,
        MetricsService,
        TimeSeriesStore,
    )

    store = TimeSeriesStore()
    collector = MetricsCollector(store, registry).start()
    server = serve_hub(api, dashboard, jwa, host=args.host, port=args.port,
                       user_id_header=args.user_id_header,
                       metrics_service=MetricsService(store))
    metrics = None
    if args.metrics_port >= 0:
        from kubeflow_tpu.utils.monitoring import MetricsHttpServer

        metrics = MetricsHttpServer(registry, args.metrics_port)
    serve_forever(server.stop, collector.stop,
                  (metrics.stop if metrics is not None else (lambda: None)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
