"""minijs — a minimal JavaScript interpreter for executing the page scripts.

Why this exists: the reference's UI tier is tested by *executing* its
frontend code against a live backend (Selenium over the jupyter-web-app —
reference testing/test_jwa.py:32-423 — and puppeteer over centraldashboard —
components/centraldashboard/test/e2e.test.ts). This image ships no JS
runtime (node/bun/deno absent, zero egress), so the framework vendors one:
a small tree-walking interpreter covering exactly the dialect the pages
are written in (webapps/frontend.py, controlplane/bootstrap.py — the
builder controls both sides of this contract).

Dialect covered (and intentionally nothing more):

- ``const``/``let`` (multi-declarator, array-destructuring patterns),
  function declarations, arrow functions (expression and block bodies,
  destructured params), ``async``/``await``
- template literals with nested ``${...}`` substitutions, string/regex
  literals, object/array literals (shorthand props, computed keys,
  spread), ``new``, ``typeof``-free — the pages never use it
- member/index/call chains, optional spread args, ternary, ``||``/``&&``,
  strict (in)equality, arithmetic with JS string-concat semantics
- ``if``/``else``, ``for...of``, ``try``/``catch``, ``throw``, ``return``
- stdlib the pages touch: ``String``/``Number``/``Array.isArray``/
  ``Object.entries``/``Object.assign``/``JSON.stringify``/``Math``/
  ``Promise.all``/``encodeURIComponent``, string ``replace`` (with regex +
  callback), array ``map/filter/find/forEach/join/slice/push/includes``,
  number ``toFixed``/``toPrecision``

**Async model**: the pages' async functions are linear awaits over fetch;
the host ``fetch`` shim is synchronous under the hood, so ``await`` simply
evaluates its operand and ``async`` functions run eagerly to completion
(``Promise.all`` maps to its argument list). This collapses the microtask
queue — correct for the pages' sequential flows, and what makes the
interpreter small enough to vendor.

**Host interop**: JS values ARE Python values (dict/list/str/float/bool/
None + an ``undefined`` sentinel); host objects (the DOM shim's elements,
fetch responses) are ordinary Python objects accessed via getattr/setattr,
so the test harness writes its browser shim in Python.
"""

from __future__ import annotations

import json as _json
import math
import re as _re
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Interpreter", "JSError", "Undefined", "undefined"]


# ---------------------------------------------------------------- values


class _UndefinedType:
    _inst: Optional["_UndefinedType"] = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


Undefined = _UndefinedType
undefined = _UndefinedType()


class JSError(Exception):
    """A thrown JS value (``throw`` / runtime errors). ``.value`` is the
    thrown value — for ``new Error(m)`` a dict with a ``message`` key."""

    def __init__(self, value):
        self.value = value
        super().__init__(js_to_string(
            value.get("message") if isinstance(value, dict) else value))


class _Return(Exception):
    def __init__(self, value):
        self.value = value


def js_truthy(v) -> bool:
    if v is undefined or v is None or v is False:
        return False
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return not (v == 0 or (isinstance(v, float) and math.isnan(v)))
    if isinstance(v, str):
        return len(v) > 0
    return True


def js_to_string(v) -> str:
    if v is undefined:
        return "undefined"
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        if v == int(v) and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return v
    if isinstance(v, list):
        return ",".join("" if x is undefined or x is None else js_to_string(x)
                        for x in v)
    if isinstance(v, dict):
        return "[object Object]"
    if callable(v):
        return "function"
    return str(v)


def js_to_number(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if v is None:
        return 0.0
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            return float(s)
        except ValueError:
            return float("nan")
    return float("nan")


def _js_regex(pattern: str, flags: str):
    f = 0
    if "i" in flags:
        f |= _re.IGNORECASE
    if "s" in flags:
        f |= _re.DOTALL
    if "m" in flags:
        f |= _re.MULTILINE
    return _re.compile(pattern, f)


class _Regex:
    def __init__(self, pattern: str, flags: str):
        self.source, self.flags = pattern, flags
        self.re = _js_regex(pattern, flags)
        self.global_ = "g" in flags


# ---------------------------------------------------------------- lexer

_PUNCT = [
    "...", "===", "!==", "=>", "<=", ">=", "&&", "||", "++", "--",
    "+=", "-=", "*=", "/=", "==", "!=",
    "(", ")", "[", "]", "{", "}", ",", ";", ":", "?", ".", "=",
    "+", "-", "*", "/", "%", "<", ">", "!",
]

_KEYWORDS = {
    "const", "let", "var", "function", "return", "if", "else", "for",
    "of", "while", "try", "catch", "finally", "throw", "new", "async",
    "await", "true", "false", "null", "undefined", "typeof", "in",
}


class _Tok:
    __slots__ = ("kind", "value", "line")

    def __init__(self, kind, value, line):
        self.kind, self.value, self.line = kind, value, line

    def __repr__(self):
        return f"{self.kind}({self.value!r})@{self.line}"


class _Lexer:
    """Produces a token list. Template literals become one token whose
    value is a list of ('str', text) / ('expr', subtokens) parts —
    substitutions are recursively lexed (nesting included)."""

    def __init__(self, src: str):
        self.src = src
        self.i = 0
        self.line = 1
        self.toks: List[_Tok] = []

    def error(self, msg):
        raise SyntaxError(f"minijs lex error line {self.line}: {msg}")

    def lex(self) -> List[_Tok]:
        while self.i < len(self.src):
            c = self.src[self.i]
            if c == "\n":
                self.line += 1
                self.i += 1
                continue
            if c in " \t\r":
                self.i += 1
                continue
            if self.src.startswith("//", self.i):
                nl = self.src.find("\n", self.i)
                self.i = len(self.src) if nl < 0 else nl
                continue
            if self.src.startswith("/*", self.i):
                end = self.src.find("*/", self.i + 2)
                if end < 0:
                    self.error("unterminated block comment")
                self.line += self.src.count("\n", self.i, end)
                self.i = end + 2
                continue
            if c == "`":
                self.toks.append(self._template())
                continue
            if c in "'\"":
                self.toks.append(self._string(c))
                continue
            if c.isdigit() or (c == "." and self.i + 1 < len(self.src)
                               and self.src[self.i + 1].isdigit()):
                self.toks.append(self._number())
                continue
            if c.isalpha() or c in "_$":
                self.toks.append(self._ident())
                continue
            if c == "/" and self._regex_allowed():
                self.toks.append(self._regex())
                continue
            for p in _PUNCT:
                if self.src.startswith(p, self.i):
                    self.toks.append(_Tok("punct", p, self.line))
                    self.i += len(p)
                    break
            else:
                self.error(f"unexpected character {c!r}")
        self.toks.append(_Tok("eof", None, self.line))
        return self.toks

    def _regex_allowed(self) -> bool:
        for t in reversed(self.toks):
            if t.kind in ("num", "str", "template", "regex"):
                return False
            if t.kind == "ident" and t.value not in _KEYWORDS:
                return False
            if t.kind == "ident":       # keyword: return /.../ is a regex
                return True
            if t.kind == "punct":
                return t.value not in (")", "]", "}")
        return True

    def _string(self, quote) -> _Tok:
        self.i += 1
        out = []
        while self.i < len(self.src):
            c = self.src[self.i]
            if c == "\\":
                out.append(self._escape())
                continue
            if c == quote:
                self.i += 1
                return _Tok("str", "".join(out), self.line)
            if c == "\n":
                self.error("unterminated string")
            out.append(c)
            self.i += 1
        self.error("unterminated string")

    def _escape(self) -> str:
        self.i += 1  # backslash
        c = self.src[self.i]
        self.i += 1
        table = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                 "0": "\0", "\n": ""}
        if c == "u":
            hexs = self.src[self.i:self.i + 4]
            self.i += 4
            return chr(int(hexs, 16))
        if c == "x":
            hexs = self.src[self.i:self.i + 2]
            self.i += 2
            return chr(int(hexs, 16))
        return table.get(c, c)

    def _number(self) -> _Tok:
        m = _re.match(r"0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+",
                      self.src[self.i:])
        text = m.group(0)
        self.i += len(text)
        if text.lower().startswith("0x"):
            return _Tok("num", float(int(text, 16)), self.line)
        return _Tok("num", float(text), self.line)

    def _ident(self) -> _Tok:
        m = _re.match(r"[A-Za-z_$][A-Za-z0-9_$]*", self.src[self.i:])
        text = m.group(0)
        self.i += len(text)
        return _Tok("ident", text, self.line)

    def _regex(self) -> _Tok:
        start = self.i
        self.i += 1  # /
        in_class = False
        body = []
        while self.i < len(self.src):
            c = self.src[self.i]
            if c == "\\":
                body.append(self.src[self.i:self.i + 2])
                self.i += 2
                continue
            if c == "[":
                in_class = True
            elif c == "]":
                in_class = False
            elif c == "/" and not in_class:
                self.i += 1
                m = _re.match(r"[a-z]*", self.src[self.i:])
                flags = m.group(0)
                self.i += len(flags)
                return _Tok("regex", ("".join(body), flags), self.line)
            elif c == "\n":
                break
            body.append(c)
            self.i += 1
        self.i = start
        self.error("unterminated regex")

    def _template(self) -> _Tok:
        self.i += 1  # backtick
        parts: List[Tuple[str, Any]] = []
        buf: List[str] = []
        while self.i < len(self.src):
            c = self.src[self.i]
            if c == "\\":
                buf.append(self._escape())
                continue
            if c == "`":
                self.i += 1
                if buf:
                    parts.append(("str", "".join(buf)))
                return _Tok("template", parts, self.line)
            if self.src.startswith("${", self.i):
                if buf:
                    parts.append(("str", "".join(buf)))
                    buf = []
                self.i += 2
                sub = self._sub_expression()
                parts.append(("expr", sub))
                continue
            if c == "\n":
                self.line += 1
            buf.append(c)
            self.i += 1
        self.error("unterminated template literal")

    def _sub_expression(self) -> List[_Tok]:
        """Lex tokens until the matching close brace of a ``${``."""
        depth = 0
        sub = _Lexer("")
        sub.src = self.src
        sub.i = self.i
        sub.line = self.line
        while sub.i < len(sub.src):
            # Peek at raw chars for the brace bookkeeping, but delegate all
            # tokenization (strings, nested templates, regexes) to the
            # sub-lexer's machinery by lexing one token at a time.
            c = sub.src[sub.i]
            if c == "}" and depth == 0:
                sub.toks.append(_Tok("eof", None, sub.line))
                self.i = sub.i + 1
                self.line = sub.line
                return sub.toks
            before = len(sub.toks)
            sub._lex_one()
            for t in sub.toks[before:]:
                if t.kind == "punct" and t.value == "{":
                    depth += 1
                elif t.kind == "punct" and t.value == "}":
                    depth -= 1
        self.error("unterminated ${...} substitution")

    def _lex_one(self):
        """Advance by exactly one token (or skip whitespace/comments)."""
        while self.i < len(self.src):
            c = self.src[self.i]
            if c == "\n":
                self.line += 1
                self.i += 1
                continue
            if c in " \t\r":
                self.i += 1
                continue
            if self.src.startswith("//", self.i):
                nl = self.src.find("\n", self.i)
                self.i = len(self.src) if nl < 0 else nl
                continue
            if self.src.startswith("/*", self.i):
                end = self.src.find("*/", self.i + 2)
                self.line += self.src.count("\n", self.i, end)
                self.i = end + 2
                continue
            break
        if self.i >= len(self.src):
            return
        c = self.src[self.i]
        if c == "`":
            self.toks.append(self._template())
        elif c in "'\"":
            self.toks.append(self._string(c))
        elif c.isdigit():
            self.toks.append(self._number())
        elif c.isalpha() or c in "_$":
            self.toks.append(self._ident())
        elif c == "/" and self._regex_allowed():
            self.toks.append(self._regex())
        else:
            for p in _PUNCT:
                if self.src.startswith(p, self.i):
                    self.toks.append(_Tok("punct", p, self.line))
                    self.i += len(p)
                    return
            self.error(f"unexpected character {c!r}")


# ---------------------------------------------------------------- parser
#
# AST nodes are plain tuples: (kind, ...). Kept positional for compactness;
# the evaluator is the single consumer.


class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.i = 0

    # -- helpers --

    def peek(self, k=0) -> _Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, value) -> bool:
        t = self.peek()
        return t.kind == "punct" and t.value == value

    def at_kw(self, word) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.value == word

    def expect(self, value) -> _Tok:
        t = self.next()
        if t.kind != "punct" or t.value != value:
            raise SyntaxError(
                f"minijs parse error line {t.line}: expected {value!r}, "
                f"got {t.kind} {t.value!r}")
        return t

    def expect_kw(self, word):
        t = self.next()
        if t.kind != "ident" or t.value != word:
            raise SyntaxError(
                f"minijs parse error line {t.line}: expected {word!r}")

    # -- entry --

    def parse_program(self):
        body = []
        while self.peek().kind != "eof":
            body.append(self.statement())
        return ("block", body)

    # -- statements --

    def statement(self):
        t = self.peek()
        if t.kind == "punct" and t.value == "{":
            return self.block()
        if t.kind == "punct" and t.value == ";":
            self.next()
            return ("empty",)
        if t.kind == "ident":
            w = t.value
            if w in ("const", "let", "var"):
                return self.var_decl()
            if w == "function":
                return self.func_decl(is_async=False)
            if w == "async" and self.peek(1).kind == "ident" \
                    and self.peek(1).value == "function":
                self.next()
                return self.func_decl(is_async=True)
            if w == "return":
                self.next()
                if self.at(";") or self.at("}") or self.peek().kind == "eof":
                    val = ("lit", undefined)
                else:
                    val = self.expression()
                self._semi()
                return ("return", val)
            if w == "if":
                return self.if_stmt()
            if w == "for":
                return self.for_stmt()
            if w == "while":
                return self.while_stmt()
            if w == "try":
                return self.try_stmt()
            if w == "throw":
                self.next()
                val = self.expression()
                self._semi()
                return ("throw", val)
        expr = self.expression()
        self._semi()
        return ("exprstmt", expr)

    def _semi(self):
        if self.at(";"):
            self.next()

    def block(self):
        self.expect("{")
        body = []
        while not self.at("}"):
            body.append(self.statement())
        self.expect("}")
        return ("block", body)

    def var_decl(self):
        kw = self.next().value  # const/let/var
        decls = []
        while True:
            decls.append(self._declarator())
            if self.at(","):
                self.next()
                continue
            break
        self._semi()
        return ("vardecl", kw, decls)

    def _declarator(self):
        if self.at("["):  # array destructuring
            self.next()
            names = []
            while not self.at("]"):
                names.append(self.next().value)
                if self.at(","):
                    self.next()
            self.expect("]")
            self.expect("=")
            return (("arraypat", names), self.expression_no_comma())
        name = self.next().value
        if self.at("="):
            self.next()
            return (name, self.expression_no_comma())
        return (name, ("lit", undefined))

    def func_decl(self, is_async):
        self.expect_kw("function")
        name = self.next().value
        params = self._param_list()
        body = self.block()
        return ("funcdecl", name, params, body, is_async)

    def _param_list(self):
        self.expect("(")
        params = []
        while not self.at(")"):
            if self.at("["):
                self.next()
                names = []
                while not self.at("]"):
                    names.append(self.next().value)
                    if self.at(","):
                        self.next()
                self.expect("]")
                params.append(("arraypat", names))
            else:
                params.append(self.next().value)
            if self.at(","):
                self.next()
        self.expect(")")
        return params

    def if_stmt(self):
        self.expect_kw("if")
        self.expect("(")
        cond = self.expression()
        self.expect(")")
        then = self.statement()
        other = None
        if self.at_kw("else"):
            self.next()
            other = self.statement()
        return ("if", cond, then, other)

    def while_stmt(self):
        self.expect_kw("while")
        self.expect("(")
        cond = self.expression()
        self.expect(")")
        return ("while", cond, self.statement())

    def for_stmt(self):
        self.expect_kw("for")
        self.expect("(")
        # Only for...of (the pages use nothing else).
        kw = self.next()  # const/let
        if kw.kind != "ident" or kw.value not in ("const", "let", "var"):
            raise SyntaxError(
                f"minijs line {kw.line}: only for (const x of ...) loops "
                "are supported")
        name = self.next().value
        self.expect_kw("of")
        it = self.expression()
        self.expect(")")
        return ("forof", name, it, self.statement())

    def try_stmt(self):
        self.expect_kw("try")
        body = self.block()
        param, handler = None, None
        if self.at_kw("catch"):
            self.next()
            if self.at("("):
                self.next()
                param = self.next().value
                self.expect(")")
            handler = self.block()
        fin = None
        if self.at_kw("finally"):
            self.next()
            fin = self.block()
        return ("try", body, param, handler, fin)

    # -- expressions (precedence climbing) --

    def expression(self):
        e = self.expression_no_comma()
        while self.at(","):
            self.next()
            e = ("seq", e, self.expression_no_comma())
        return e

    def expression_no_comma(self):
        return self.assignment()

    def assignment(self):
        left = self.ternary()
        if self.at("="):
            self.next()
            right = self.assignment()
            return ("assign", left, right)
        for op in ("+=", "-=", "*=", "/="):
            if self.at(op):
                self.next()
                right = self.assignment()
                return ("assign", left, ("binop", op[0], left, right))
        return left

    def ternary(self):
        cond = self.logical_or()
        if self.at("?"):
            self.next()
            a = self.assignment()
            self.expect(":")
            b = self.assignment()
            return ("ternary", cond, a, b)
        return cond

    def logical_or(self):
        e = self.logical_and()
        while self.at("||"):
            self.next()
            e = ("or", e, self.logical_and())
        return e

    def logical_and(self):
        e = self.equality()
        while self.at("&&"):
            self.next()
            e = ("and", e, self.equality())
        return e

    def equality(self):
        e = self.relational()
        while True:
            for op in ("===", "!==", "==", "!="):
                if self.at(op):
                    self.next()
                    e = ("binop", op, e, self.relational())
                    break
            else:
                return e

    def relational(self):
        e = self.additive()
        while True:
            for op in ("<=", ">=", "<", ">"):
                if self.at(op):
                    self.next()
                    e = ("binop", op, e, self.additive())
                    break
            else:
                return e

    def additive(self):
        e = self.multiplicative()
        while self.at("+") or self.at("-"):
            op = self.next().value
            e = ("binop", op, e, self.multiplicative())
        return e

    def multiplicative(self):
        e = self.unary()
        while self.at("*") or self.at("/") or self.at("%"):
            op = self.next().value
            e = ("binop", op, e, self.unary())
        return e

    def unary(self):
        if self.at("!"):
            self.next()
            return ("not", self.unary())
        if self.at("-"):
            self.next()
            return ("neg", self.unary())
        if self.at("+"):
            self.next()
            return ("pos", self.unary())
        if self.at_kw("await"):
            self.next()
            return ("await", self.unary())
        if self.at_kw("new"):
            self.next()
            callee = self.postfix(self.primary(), no_call=True)
            args = self._args() if self.at("(") else []
            return ("new", callee, args)
        if self.at_kw("typeof"):
            self.next()
            return ("typeof", self.unary())
        return self.postfix(self.primary())

    def postfix(self, e, no_call=False):
        while True:
            if self.at("."):
                self.next()
                e = ("member", e, self.next().value)
            elif self.at("["):
                self.next()
                idx = self.expression()
                self.expect("]")
                e = ("index", e, idx)
            elif self.at("(") and not no_call:
                e = ("call", e, self._args())
            else:
                return e

    def _args(self):
        self.expect("(")
        args = []
        while not self.at(")"):
            if self.at("..."):
                self.next()
                args.append(("spread", self.expression_no_comma()))
            else:
                args.append(self.expression_no_comma())
            if self.at(","):
                self.next()
        self.expect(")")
        return args

    def _arrow_ahead(self) -> bool:
        """At '(' — does this parenthesized group end with '=>'?"""
        depth = 0
        j = self.i
        while j < len(self.toks):
            t = self.toks[j]
            if t.kind == "punct":
                if t.value in ("(", "[", "{"):
                    depth += 1
                elif t.value in (")", "]", "}"):
                    depth -= 1
                    if depth == 0:
                        nxt = self.toks[j + 1]
                        return nxt.kind == "punct" and nxt.value == "=>"
            j += 1
        return False

    def primary(self):
        t = self.peek()
        if t.kind == "num":
            self.next()
            return ("lit", t.value)
        if t.kind == "str":
            self.next()
            return ("lit", t.value)
        if t.kind == "regex":
            self.next()
            return ("regexlit", t.value[0], t.value[1])
        if t.kind == "template":
            self.next()
            parts = []
            for kind, payload in t.value:
                if kind == "str":
                    parts.append(("str", payload))
                else:
                    parts.append(("expr", _Parser(payload).expression()))
            return ("template", parts)
        if t.kind == "punct":
            if t.value == "(":
                if self._arrow_ahead():
                    return self._arrow(is_async=False)
                self.next()
                e = self.expression()
                self.expect(")")
                return self.postfix(e)
            if t.value == "[":
                return self._array_literal()
            if t.value == "{":
                return self._object_literal()
        if t.kind == "ident":
            w = t.value
            if w == "true":
                self.next()
                return ("lit", True)
            if w == "false":
                self.next()
                return ("lit", False)
            if w == "null":
                self.next()
                return ("lit", None)
            if w == "undefined":
                self.next()
                return ("lit", undefined)
            if w == "async":
                nxt = self.peek(1)
                if nxt.kind == "punct" and nxt.value == "(":
                    self.next()
                    return self._arrow(is_async=True)
                if nxt.kind == "ident" and self.peek(2).kind == "punct" \
                        and self.peek(2).value == "=>":
                    self.next()
                    return self._arrow(is_async=True)
            if w == "function":
                return self._func_expr(is_async=False)
            # single-param arrow: x => ...
            nxt = self.peek(1)
            if nxt.kind == "punct" and nxt.value == "=>":
                return self._arrow(is_async=False)
            self.next()
            return ("name", w)
        raise SyntaxError(
            f"minijs parse error line {t.line}: unexpected "
            f"{t.kind} {t.value!r}")

    def _func_expr(self, is_async):
        self.expect_kw("function")
        name = None
        if self.peek().kind == "ident":
            name = self.next().value
        params = self._param_list()
        body = self.block()
        return ("func", name, params, body, is_async)

    def _arrow(self, is_async):
        if self.at("("):
            params = self._param_list()
        else:
            params = [self.next().value]
        self.expect("=>")
        if self.at("{"):
            body = self.block()
            return ("func", None, params, body, is_async)
        body = self.expression_no_comma()
        return ("func", None, params, ("return", body), is_async)

    def _array_literal(self):
        self.expect("[")
        items = []
        while not self.at("]"):
            if self.at("..."):
                self.next()
                items.append(("spread", self.expression_no_comma()))
            else:
                items.append(self.expression_no_comma())
            if self.at(","):
                self.next()
        self.expect("]")
        return self.postfix(("array", items))

    def _object_literal(self):
        self.expect("{")
        props = []
        while not self.at("}"):
            if self.at("..."):
                self.next()
                props.append(("spreadprop", self.expression_no_comma()))
            elif self.at("["):
                self.next()
                key = self.expression()
                self.expect("]")
                self.expect(":")
                props.append(("computed", key, self.expression_no_comma()))
            else:
                t = self.next()
                key = t.value if t.kind in ("ident", "str") else \
                    js_to_string(t.value)
                if self.at(":"):
                    self.next()
                    props.append(("prop", key, self.expression_no_comma()))
                elif self.at("(") :
                    params = self._param_list()
                    body = self.block()
                    props.append(
                        ("prop", key, ("func", key, params, body, False)))
                else:  # shorthand
                    props.append(("prop", key, ("name", key)))
            if self.at(","):
                self.next()
        self.expect("}")
        return self.postfix(("object", props))


# ---------------------------------------------------------------- runtime


class _Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None, vars=None):
        self.vars: Dict[str, Any] = vars or {}
        self.parent = parent

    def get(self, name):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise JSError({"message": f"{name} is not defined"})

    def set(self, name, value):
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        # implicit global (the pages only assign to declared names; this
        # matches sloppy-mode JS)
        root = self
        while root.parent is not None:
            root = root.parent
        root.vars[name] = value

    def declare(self, name, value):
        self.vars[name] = value


class _JSFunction:
    __slots__ = ("params", "body", "env", "interp", "name")

    def __init__(self, name, params, body, env, interp):
        self.name, self.params, self.body = name, params, body
        self.env, self.interp = env, interp

    def __call__(self, *args):
        env = _Env(self.env)
        for i, p in enumerate(self.params):
            v = args[i] if i < len(args) else undefined
            if isinstance(p, tuple) and p[0] == "arraypat":
                seq = v if isinstance(v, (list, tuple)) else []
                for j, n in enumerate(p[1]):
                    env.declare(n, seq[j] if j < len(seq) else undefined)
            else:
                env.declare(p, v)
        try:
            self.interp._exec(self.body, env)
        except _Return as r:
            return r.value
        return undefined


def _make_error(*args):
    msg = js_to_string(args[0]) if args else ""
    return {"message": msg, "stack": msg, "name": "Error"}


class Interpreter:
    """One global scope + stdlib. ``run(src)`` executes a script;
    ``env`` is exposed for host shims to inject globals and to call back
    into JS functions (they are plain Python callables)."""

    def __init__(self, globals: Optional[Dict[str, Any]] = None):
        self.global_env = _Env(vars=dict(globals or {}))
        g = self.global_env.vars
        g.setdefault("JSON", {
            "stringify": lambda v, *a: _json_stringify(v),
            "parse": lambda s, *a: _json.loads(s),
        })
        g.setdefault("Math", {
            "min": lambda *a: min(js_to_number(x) for x in a)
            if a else float("inf"),
            "max": lambda *a: max(js_to_number(x) for x in a)
            if a else float("-inf"),
            "floor": lambda x: float(math.floor(js_to_number(x))),
            "ceil": lambda x: float(math.ceil(js_to_number(x))),
            "round": lambda x: float(math.floor(js_to_number(x) + 0.5)),
            "abs": lambda x: abs(js_to_number(x)),
        })
        g.setdefault("Object", {
            "entries": lambda o: [[k, v] for k, v in o.items()]
            if isinstance(o, dict) else [],
            "keys": lambda o: list(o.keys()) if isinstance(o, dict) else [],
            "values": lambda o: list(o.values())
            if isinstance(o, dict) else [],
            "assign": _object_assign,
        })
        g.setdefault("Array", {
            "isArray": lambda v: isinstance(v, list),
            "from": lambda v, *a: list(v),
        })
        g.setdefault("Promise", {
            # async collapses to sync: an "awaited" value IS the value.
            "all": lambda xs: list(xs),
            "resolve": lambda x=undefined: x,
        })
        g.setdefault("String", js_to_string)
        g.setdefault("Number", js_to_number)
        g.setdefault("Boolean", js_truthy)
        g.setdefault("Error", _make_error)
        g.setdefault("encodeURIComponent", _encode_uri_component)
        g.setdefault("decodeURIComponent", _decode_uri_component)
        g.setdefault("parseInt", lambda s, base=10.0:
                     float(int(js_to_string(s).strip() or "0",
                               int(base or 10))))
        g.setdefault("parseFloat", js_to_number)
        g.setdefault("isNaN", lambda v: math.isnan(js_to_number(v)))
        g.setdefault("console", {
            "log": lambda *a: None, "error": lambda *a: None,
            "warn": lambda *a: None,
        })
        g.setdefault("globalThis", g)

    # -- public --

    def run(self, src: str):
        ast = _Parser(_Lexer(src).lex()).parse_program()
        # Top-level declarations are script-globals: execute the program
        # body directly in the global scope (no wrapper block scope).
        for st in ast[1]:
            if st[0] == "funcdecl":
                self.global_env.declare(
                    st[1], _JSFunction(st[1], st[2], st[3],
                                       self.global_env, self))
        for st in ast[1]:
            self._exec(st, self.global_env)
        return undefined

    @property
    def globals(self) -> Dict[str, Any]:
        return self.global_env.vars

    # -- statements --

    def _exec(self, node, env):
        kind = node[0]
        if kind == "block":
            block_env = _Env(env)
            # hoist function declarations (the pages call helpers defined
            # later in the script — e.g. refresh() before its decl)
            for st in node[1]:
                if st[0] == "funcdecl":
                    block_env.declare(
                        st[1],
                        _JSFunction(st[1], st[2], st[3], block_env, self))
            for st in node[1]:
                self._exec(st, block_env)
            return undefined
        if kind == "exprstmt":
            self._eval(node[1], env)
            return undefined
        if kind == "empty":
            return undefined
        if kind == "vardecl":
            for target, init in node[2]:
                v = self._eval(init, env)
                if isinstance(target, tuple) and target[0] == "arraypat":
                    seq = v if isinstance(v, (list, tuple)) else []
                    for j, n in enumerate(target[1]):
                        env.declare(n, seq[j] if j < len(seq) else undefined)
                else:
                    env.declare(target, v)
            return undefined
        if kind == "funcdecl":
            # already hoisted; re-binding is harmless
            env.declare(node[1],
                        _JSFunction(node[1], node[2], node[3], env, self))
            return undefined
        if kind == "return":
            raise _Return(self._eval(node[1], env))
        if kind == "if":
            if js_truthy(self._eval(node[1], env)):
                self._exec(node[2], env)
            elif node[3] is not None:
                self._exec(node[3], env)
            return undefined
        if kind == "while":
            guard = 0
            while js_truthy(self._eval(node[1], env)):
                self._exec(node[2], env)
                guard += 1
                if guard > 1_000_000:
                    raise JSError({"message": "while loop exceeded 1e6 "
                                   "iterations (minijs guard)"})
            return undefined
        if kind == "forof":
            it = self._eval(node[2], env)
            if isinstance(it, dict):
                it = list(it.values())
            for item in list(it):
                loop_env = _Env(env)
                loop_env.declare(node[1], item)
                self._exec(node[3], loop_env)
            return undefined
        if kind == "try":
            _, body, param, handler, fin = node
            try:
                self._exec(body, env)
            except JSError as e:
                if handler is None:
                    raise  # try/finally: the error propagates after fin
                henv = _Env(env)
                if param:
                    henv.declare(param, e.value)
                self._exec(handler, henv)
            finally:
                if fin is not None:
                    self._exec(fin, env)
            return undefined
        if kind == "throw":
            raise JSError(self._eval(node[1], env))
        raise AssertionError(f"unknown statement {kind}")

    # -- expressions --

    def _eval(self, node, env):
        kind = node[0]
        if kind == "lit":
            return node[1]
        if kind == "name":
            return env.get(node[1])
        if kind == "template":
            out = []
            for pk, payload in node[1]:
                if pk == "str":
                    out.append(payload)
                else:
                    out.append(js_to_string(self._eval(payload, env)))
            return "".join(out)
        if kind == "regexlit":
            return _Regex(node[1], node[2])
        if kind == "array":
            out = []
            for item in node[1]:
                if item[0] == "spread":
                    out.extend(list(self._eval(item[1], env)))
                else:
                    out.append(self._eval(item, env))
            return out
        if kind == "object":
            obj: Dict[str, Any] = {}
            for prop in node[1]:
                if prop[0] == "prop":
                    obj[prop[1]] = self._eval(prop[2], env)
                elif prop[0] == "computed":
                    obj[js_to_string(self._eval(prop[1], env))] = \
                        self._eval(prop[2], env)
                else:  # spreadprop
                    src = self._eval(prop[1], env)
                    if isinstance(src, dict):
                        obj.update(src)
            return obj
        if kind == "func":
            return _JSFunction(node[1], node[2], node[3], env, self)
        if kind == "seq":
            self._eval(node[1], env)
            return self._eval(node[2], env)
        if kind == "assign":
            return self._assign(node[1], self._eval(node[2], env), env)
        if kind == "ternary":
            return self._eval(node[2] if js_truthy(self._eval(node[1], env))
                              else node[3], env)
        if kind == "or":
            left = self._eval(node[1], env)
            return left if js_truthy(left) else self._eval(node[2], env)
        if kind == "and":
            left = self._eval(node[1], env)
            return self._eval(node[2], env) if js_truthy(left) else left
        if kind == "not":
            return not js_truthy(self._eval(node[1], env))
        if kind == "neg":
            return -js_to_number(self._eval(node[1], env))
        if kind == "pos":
            return js_to_number(self._eval(node[1], env))
        if kind == "await":
            return self._eval(node[1], env)  # async collapses to sync
        if kind == "typeof":
            return _js_typeof(self._eval(node[1], env))
        if kind == "binop":
            return self._binop(node[1], self._eval(node[2], env),
                               self._eval(node[3], env))
        if kind == "member":
            return self._member_get(self._eval(node[1], env), node[2])
        if kind == "index":
            obj = self._eval(node[1], env)
            idx = self._eval(node[2], env)
            if isinstance(obj, (list, str)) and isinstance(
                    idx, (int, float)) and not isinstance(idx, bool):
                i = int(idx)
                if 0 <= i < len(obj):
                    return obj[i]
                return undefined
            return self._member_get(obj, js_to_string(idx))
        if kind == "call":
            return self._call(node, env)
        if kind == "new":
            ctor = self._eval(node[1], env)
            args = self._eval_args(node[2], env)
            return ctor(*args)
        if kind == "spread":
            raise JSError({"message": "spread outside call/array"})
        raise AssertionError(f"unknown expression {kind}")

    def _eval_args(self, arg_nodes, env):
        args = []
        for a in arg_nodes:
            if a[0] == "spread":
                args.extend(list(self._eval(a[1], env)))
            else:
                args.append(self._eval(a, env))
        return args

    def _call(self, node, env):
        callee = node[1]
        args = self._eval_args(node[2], env)
        if callee[0] == "member":
            obj = self._eval(callee[1], env)
            fn = self._member_get(obj, callee[2])
        elif callee[0] == "index":
            obj = self._eval(callee[1], env)
            fn = self._member_get(obj, js_to_string(
                self._eval(callee[2], env)))
        else:
            fn = self._eval(callee, env)
        if not callable(fn):
            name = callee[2] if callee[0] == "member" else \
                (callee[1] if callee[0] == "name" else "?")
            raise JSError({"message": f"{name} is not a function"})
        return fn(*args)

    def _assign(self, target, value, env):
        kind = target[0]
        if kind == "name":
            env.set(target[1], value)
            return value
        if kind == "member":
            obj = self._eval(target[1], env)
            self._member_set(obj, target[2], value)
            return value
        if kind == "index":
            obj = self._eval(target[1], env)
            idx = self._eval(target[2], env)
            if isinstance(obj, list) and isinstance(
                    idx, (int, float)) and not isinstance(idx, bool):
                i = int(idx)
                while len(obj) <= i:
                    obj.append(undefined)
                obj[i] = value
            elif isinstance(obj, dict):
                obj[js_to_string(idx)] = value
            else:
                self._member_set(obj, js_to_string(idx), value)
            return value
        raise JSError({"message": "invalid assignment target"})

    def _binop(self, op, a, b):
        if op == "+":
            if isinstance(a, str) or isinstance(b, str) or \
                    isinstance(a, (list, dict)) or isinstance(b, (list, dict)):
                return js_to_string(a) + js_to_string(b)
            return js_to_number(a) + js_to_number(b)
        if op == "-":
            return js_to_number(a) - js_to_number(b)
        if op == "*":
            return js_to_number(a) * js_to_number(b)
        if op == "/":
            bn = js_to_number(b)
            an = js_to_number(a)
            if bn == 0:
                if an == 0:
                    return float("nan")
                return float("inf") if an > 0 else float("-inf")
            return an / bn
        if op == "%":
            return math.fmod(js_to_number(a), js_to_number(b))
        if op in ("===", "=="):
            return _strict_eq(a, b)
        if op in ("!==", "!="):
            return not _strict_eq(a, b)
        if op == "<":
            return self._compare(a, b, lambda x, y: x < y)
        if op == ">":
            return self._compare(a, b, lambda x, y: x > y)
        if op == "<=":
            return self._compare(a, b, lambda x, y: x <= y)
        if op == ">=":
            return self._compare(a, b, lambda x, y: x >= y)
        raise AssertionError(op)

    @staticmethod
    def _compare(a, b, fn):
        if isinstance(a, str) and isinstance(b, str):
            return fn(a, b)
        return fn(js_to_number(a), js_to_number(b))

    # -- member protocol --

    def _member_get(self, obj, name):
        if obj is undefined or obj is None:
            raise JSError({"message":
                           f"cannot read property {name!r} of "
                           f"{js_to_string(obj)}"})
        if isinstance(obj, dict):
            if name in obj:
                return obj[name]
            return undefined
        if isinstance(obj, list):
            return _array_member(obj, name, self)
        if isinstance(obj, str):
            return _string_member(obj, name)
        if isinstance(obj, bool):
            return undefined
        if isinstance(obj, (int, float)):
            return _number_member(obj, name)
        if isinstance(obj, _Regex):
            return {"source": obj.source, "flags": obj.flags}.get(
                name, undefined)
        # host object
        try:
            v = getattr(obj, name)
        except AttributeError:
            return undefined
        return v

    def _member_set(self, obj, name, value):
        if isinstance(obj, dict):
            obj[name] = value
            return
        if isinstance(obj, list):
            if name == "length":
                n = int(js_to_number(value))
                del obj[n:]
                return
            raise JSError({"message": f"cannot set {name} on array"})
        # host object
        setattr(obj, name, value)


def _strict_eq(a, b):
    if a is undefined or b is undefined:
        return a is b
    if a is None or b is None:
        return a is b
    if isinstance(a, bool) or isinstance(b, bool):
        return isinstance(a, bool) and isinstance(b, bool) and a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b


def _js_typeof(v):
    if v is undefined:
        return "undefined"
    if v is None:
        return "object"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    if isinstance(v, str):
        return "string"
    if callable(v):
        return "function"
    return "object"


def _object_assign(target, *sources):
    for s in sources:
        if isinstance(s, dict):
            target.update(s)
    return target


def _json_stringify(v):
    def conv(x):
        if x is undefined:
            return None
        if isinstance(x, dict):
            return {k: conv(val) for k, val in x.items()
                    if val is not undefined}
        if isinstance(x, list):
            return [conv(i) for i in x]
        if isinstance(x, float) and x == int(x) and abs(x) < 1e15:
            return int(x)
        return x
    return _json.dumps(conv(v), separators=(",", ":"))


def _encode_uri_component(s):
    from urllib.parse import quote

    return quote(js_to_string(s), safe="!'()*-._~")


def _decode_uri_component(s):
    from urllib.parse import unquote

    return unquote(js_to_string(s))


# -- built-in member banks --


def _array_member(arr: list, name: str, interp: Interpreter):
    if name == "length":
        return float(len(arr))
    if name == "map":
        return lambda fn, *a: [fn(v, float(i), arr)
                               for i, v in enumerate(list(arr))]
    if name == "filter":
        return lambda fn, *a: [v for i, v in enumerate(list(arr))
                               if js_truthy(fn(v, float(i), arr))]
    if name == "forEach":
        def _each(fn, *a):
            for i, v in enumerate(list(arr)):
                fn(v, float(i), arr)
            return undefined
        return _each
    if name == "find":
        def _find(fn, *a):
            for i, v in enumerate(list(arr)):
                if js_truthy(fn(v, float(i), arr)):
                    return v
            return undefined
        return _find
    if name == "findIndex":
        def _findi(fn, *a):
            for i, v in enumerate(list(arr)):
                if js_truthy(fn(v, float(i), arr)):
                    return float(i)
            return -1.0
        return _findi
    if name == "join":
        return lambda sep=",": js_to_string(sep).join(
            "" if v is undefined or v is None else js_to_string(v)
            for v in arr)
    if name == "slice":
        def _slice(start=0.0, end=None):
            s = int(js_to_number(start))
            e = len(arr) if end is None else int(js_to_number(end))
            return list(arr[s:e])
        return _slice
    if name == "push":
        def _push(*vals):
            arr.extend(vals)
            return float(len(arr))
        return _push
    if name == "pop":
        return lambda: arr.pop() if arr else undefined
    if name == "includes":
        return lambda v: any(_strict_eq(v, x) for x in arr)
    if name == "indexOf":
        def _index(v):
            for i, x in enumerate(arr):
                if _strict_eq(v, x):
                    return float(i)
            return -1.0
        return _index
    if name == "concat":
        def _concat(*others):
            out = list(arr)
            for o in others:
                if isinstance(o, list):
                    out.extend(o)
                else:
                    out.append(o)
            return out
        return _concat
    if name == "some":
        return lambda fn: any(js_truthy(fn(v, float(i), arr))
                              for i, v in enumerate(list(arr)))
    if name == "every":
        return lambda fn: all(js_truthy(fn(v, float(i), arr))
                              for i, v in enumerate(list(arr)))
    if name == "flat":
        def _flat(depth=1.0):
            out = []
            for v in arr:
                if isinstance(v, list) and depth >= 1:
                    out.extend(v)
                else:
                    out.append(v)
            return out
        return _flat
    if name == "sort":
        def _sort(fn=None):
            if fn is None:
                arr.sort(key=js_to_string)
            else:
                import functools
                arr.sort(key=functools.cmp_to_key(
                    lambda a, b: -1 if js_to_number(fn(a, b)) < 0
                    else (1 if js_to_number(fn(a, b)) > 0 else 0)))
            return arr
        return _sort
    if name == "reverse":
        def _rev():
            arr.reverse()
            return arr
        return _rev
    return undefined


def _string_member(s: str, name: str):
    if name == "length":
        return float(len(s))
    if name == "replace":
        def _replace(pat, repl):
            def do_one(m):
                if callable(repl):
                    groups = [m.group(0)] + [
                        g if g is not None else undefined
                        for g in m.groups()]
                    return js_to_string(repl(*groups))
                # $1-style backrefs are not used by the pages; treat the
                # replacement as a literal string.
                return js_to_string(repl)
            if isinstance(pat, _Regex):
                return pat.re.sub(do_one, s, count=0 if pat.global_ else 1)
            pat_s = js_to_string(pat)
            if callable(repl):
                idx = s.find(pat_s)
                if idx < 0:
                    return s
                return s[:idx] + js_to_string(repl(pat_s)) + \
                    s[idx + len(pat_s):]
            return s.replace(pat_s, js_to_string(repl), 1)
        return _replace
    if name == "includes":
        return lambda sub: js_to_string(sub) in s
    if name == "startsWith":
        return lambda sub: s.startswith(js_to_string(sub))
    if name == "endsWith":
        return lambda sub: s.endswith(js_to_string(sub))
    if name == "indexOf":
        return lambda sub: float(s.find(js_to_string(sub)))
    if name == "slice":
        def _slice(start=0.0, end=None):
            st = int(js_to_number(start))
            e = len(s) if end is None else int(js_to_number(end))
            return s[st:e]
        return _slice
    if name == "split":
        def _split(sep=None, *a):
            if sep is None:
                return [s]
            if isinstance(sep, _Regex):
                return sep.re.split(s)
            sep_s = js_to_string(sep)
            if sep_s == "":
                return list(s)
            return s.split(sep_s)
        return _split
    if name == "toLowerCase":
        return lambda: s.lower()
    if name == "toUpperCase":
        return lambda: s.upper()
    if name == "trim":
        return lambda: s.strip()
    if name == "charAt":
        return lambda i=0.0: s[int(js_to_number(i))] \
            if 0 <= int(js_to_number(i)) < len(s) else ""
    if name == "repeat":
        return lambda n: s * int(js_to_number(n))
    if name == "padStart":
        return lambda n, fill=" ": s.rjust(int(js_to_number(n)),
                                           js_to_string(fill) or " ")
    if name == "match":
        def _match(pat):
            if not isinstance(pat, _Regex):
                pat = _Regex(js_to_string(pat), "")
            if pat.global_:
                return [m.group(0) for m in pat.re.finditer(s)] or None
            m = pat.re.search(s)
            if m is None:
                return None
            return [m.group(0)] + [g if g is not None else undefined
                                   for g in m.groups()]
        return _match
    return undefined


def _number_member(n, name: str):
    if name == "toFixed":
        return lambda digits=0.0: f"{float(n):.{int(js_to_number(digits))}f}"
    if name == "toPrecision":
        def _prec(digits=None):
            if digits is None:
                return js_to_string(n)
            d = int(js_to_number(digits))
            out = f"{float(n):.{d}g}"
            # JS pads to the requested significant digits
            if "e" not in out and "." not in out and len(
                    out.lstrip("-")) < d:
                out += "." + "0" * (d - len(out.lstrip("-")))
            return out
        return _prec
    if name == "toString":
        return lambda *a: js_to_string(n)
    return undefined
