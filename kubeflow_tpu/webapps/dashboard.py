"""Central dashboard server API: workgroup onboarding + environment info.

Rebuild of the reference centraldashboard Express backend: the namespaced
workgroup API (app/api_workgroup.ts:247-381 — exists / create / env-info /
nuke-self / get-all-namespaces / get-contributors / add- and
remove-contributor) and the identity-attach middleware
(app/attach_user_middleware.ts, trusted header). Profile/binding work is
delegated to kfam (AccessManagement), exactly as the reference dashboard
proxies /api/workgroup onto the kfam REST service (app/server.ts:25-38).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.controlplane.kfam.service import (
    AccessManagement,
    Binding,
    KfamError,
)
from kubeflow_tpu.webapps.router import JsonHttpServer, Request, RestError, Router


def _kfam_guard(fn):
    def wrapped(*a, **kw):
        try:
            return fn(*a, **kw)
        except KfamError as e:
            raise RestError(e.status, str(e))
    return wrapped


class DashboardApi:
    """Workgroup API over kfam + the platform config."""

    def __init__(self, am: AccessManagement, *, platform_name: str = "tpu"):
        self.am = am
        self.api = am.api
        self.platform_name = platform_name

    # ---------------- operations ----------------

    def exists(self, caller: str) -> Dict[str, Any]:
        """api_workgroup.ts:247-271 — has the user onboarded?"""
        if not caller:
            return {"hasAuth": False, "user": "", "hasWorkgroup": False}
        return {
            "hasAuth": True,
            "user": caller,
            "hasWorkgroup": self.am.profile_exists(caller),
        }

    @_kfam_guard
    def create_workgroup(self, caller: str, body: Dict[str, Any]) -> Dict:
        if not caller:
            raise RestError(401, "missing identity header")
        namespace = body.get("namespace") or _default_namespace(caller)
        self.am.create_profile(caller, namespace, owner=caller)
        return {"message": f"Created namespace {namespace}"}

    @_kfam_guard
    def nuke_self(self, caller: str) -> Dict:
        """nuke-self: delete the caller's own profile (cascade removes the
        namespace; api_workgroup.ts:322-333)."""
        if not caller:
            raise RestError(401, "missing identity header")
        for p in self.api.list("Profile", copy=False):
            if p.spec.owner == caller:
                self.am.delete_profile(caller, p.metadata.name)
                return {"message": f"Removed namespace/profile {p.metadata.name}"}
        raise RestError(404, f"no profile owned by {caller}")

    def env_info(self, caller: str) -> Dict[str, Any]:
        """env-info: the namespaces the user can act in + platform info."""
        namespaces = [
            {"namespace": b.namespace, "role": b.role}
            for b in self.am.list_bindings(user=caller)
        ] if caller else []
        platform = {"kind": self.platform_name, "components": []}
        pcs = self.api.list("PlatformConfig", copy=False)
        if pcs:
            platform["components"] = list(pcs[0].status.applied_components)
            platform["defaultSliceType"] = pcs[0].spec.default_slice_type
        return {
            "user": caller,
            "isClusterAdmin": bool(caller)
            and self.am.sar.is_cluster_admin(caller),
            "namespaces": namespaces,
            "platform": platform,
        }

    def all_namespaces(self, caller: str) -> List[List[str]]:
        """get-all-namespaces: tabular [ns, owner, contributors] rows
        (api_workgroup.ts:334-360)."""
        if not caller:
            raise RestError(401, "missing identity header")
        table: Dict[str, Dict[str, Any]] = {}
        for b in self.am.list_bindings():
            row = table.setdefault(b.namespace,
                                   {"owner": "", "contributors": []})
            if b.role == "admin":
                prof = self.api.try_get("Profile", b.namespace)
                if prof is not None and prof.spec.owner == b.user:
                    row["owner"] = b.user
                    continue
            row["contributors"].append(b.user)
        return [
            [ns, row["owner"], ", ".join(sorted(set(row["contributors"])))]
            for ns, row in sorted(table.items())
        ]

    def contributors(self, caller: str, namespace: str) -> List[str]:
        if not caller:
            raise RestError(401, "missing identity header")
        prof = self.api.try_get("Profile", namespace)
        owner = prof.spec.owner if prof is not None else ""
        return sorted({
            b.user for b in self.am.list_bindings(namespace=namespace)
            if b.user != owner
        })

    @_kfam_guard
    def add_contributor(self, caller: str, namespace: str,
                        body: Dict[str, Any]) -> List[str]:
        self.am.create_binding(caller, Binding(
            user=body["contributor"], namespace=namespace,
            role=body.get("role", "edit"),
        ))
        return self.contributors(caller, namespace)

    @_kfam_guard
    def remove_contributor(self, caller: str, namespace: str,
                           body: Dict[str, Any]) -> List[str]:
        self.am.delete_binding(caller, Binding(
            user=body["contributor"], namespace=namespace,
            role=body.get("role", "edit"),
        ))
        return self.contributors(caller, namespace)

    # ---------------- HTTP ----------------

    def router(self) -> Router:
        r = Router()
        r.get("/api/workgroup/exists", lambda q: self.exists(q.caller))
        r.post("/api/workgroup/create",
               lambda q: self.create_workgroup(q.caller, q.body))
        r.delete("/api/workgroup/nuke-self",
                 lambda q: self.nuke_self(q.caller))
        r.get("/api/workgroup/env-info", lambda q: self.env_info(q.caller))
        r.get("/api/workgroup/get-all-namespaces",
              lambda q: self.all_namespaces(q.caller))
        r.get("/api/workgroup/get-contributors/<ns>",
              lambda q: self.contributors(q.caller, q.params["ns"]))
        r.post("/api/workgroup/add-contributor/<ns>",
               lambda q: self.add_contributor(q.caller, q.params["ns"],
                                              q.body))
        r.delete("/api/workgroup/remove-contributor/<ns>",
                 lambda q: self.remove_contributor(q.caller, q.params["ns"],
                                                   q.body))
        r.get("/healthz", lambda q: {"status": "ok"})
        return r

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> JsonHttpServer:
        return JsonHttpServer(
            self.router(), host=host, port=port,
            user_id_header=self.am.user_id_header,
        ).start()


def _default_namespace(user: str) -> str:
    """Derive a namespace from the user identity the way the reference
    defaults to the username (api_workgroup.ts:276)."""
    base = user.split("@")[0].lower()
    safe = "".join(c if c.isalnum() or c == "-" else "-" for c in base)
    return safe.strip("-") or "workgroup"
