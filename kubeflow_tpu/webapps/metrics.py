"""Dashboard time-series metrics plane.

Rebuild of the reference centraldashboard's pluggable ``MetricsService``
(app/metrics_service.ts:21-42: getNodeCpuUtilization / getPodCpuUtilization
/ getPodMemoryUsage backed by a Stackdriver impl,
app/stackdriver_metrics_service.ts:15-196). The TPU twist: there is no
cloud-monitoring dependency — the platform samples its own sources into an
in-memory ring of time series:

- host CPU utilisation (/proc/stat deltas — the reference's "node CPU"),
- TPU HBM usage per local device (jax device memory_stats; the reference's
  GPU analogue simply didn't exist),
- any gauge/counter in a ``MetricsRegistry`` (so controller metrics,
  ``kftpu_availability``, and job tokens/sec series appear in the same
  query surface the dashboard reads).

Query surface: ``GET /api/metrics/<series>?window=600`` returning
``{series, points: [{t, value, labels}]}``, mirroring the reference's
``/api/metrics/:type((node|podcpu|podmem))`` route (app/api.ts).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from kubeflow_tpu.utils import get_logger
from kubeflow_tpu.utils.monitoring import MetricsRegistry
from kubeflow_tpu.webapps.router import Request, RestError, Router

log = get_logger("metrics")

LabelKV = Tuple[Tuple[str, str], ...]


@dataclasses.dataclass(frozen=True)
class Point:
    t: float
    value: float
    labels: LabelKV = ()


class TimeSeriesStore:
    """Bounded in-memory series store: newest-last deques keyed by
    (series name, label set), pruned by age on write and on read.

    Keying by label set keeps distinct streams (per-device tpu_hbm_*,
    labeled registry counters) from interleaving into one sawtooth line;
    ``query`` merges them time-ordered, ``query_groups`` returns each
    stream separately for per-label-set rendering."""

    def __init__(self, retention_s: float = 3600.0, max_points: int = 4096):
        self.retention_s = retention_s
        self.max_points = max_points
        self._series: Dict[Tuple[str, LabelKV], Deque[Point]] = {}
        self._lock = threading.Lock()

    def record(self, series: str, value: float, *,
               t: Optional[float] = None, labels: LabelKV = ()) -> None:
        p = Point(t=time.time() if t is None else t, value=float(value),
                  labels=labels)
        with self._lock:
            dq = self._series.setdefault(
                (series, labels), deque(maxlen=self.max_points)
            )
            dq.append(p)
            cutoff = p.t - self.retention_s
            while dq and dq[0].t < cutoff:
                dq.popleft()

    def query(self, series: str, window_s: float = 600.0,
              now: Optional[float] = None) -> List[Point]:
        cutoff = (time.time() if now is None else now) - window_s
        with self._lock:
            pts = [
                p
                for (name, _labels), dq in self._series.items()
                if name == series
                for p in dq
                if p.t >= cutoff
            ]
        pts.sort(key=lambda p: p.t)
        return pts

    def query_groups(
        self, series: str, window_s: float = 600.0,
        now: Optional[float] = None,
    ) -> List[Tuple[LabelKV, List[Point]]]:
        """Points for ``series`` split per label set (sorted by labels)."""
        cutoff = (time.time() if now is None else now) - window_s
        with self._lock:
            groups = [
                (labels, [p for p in dq if p.t >= cutoff])
                for (name, labels), dq in self._series.items()
                if name == series
            ]
        return sorted((g for g in groups if g[1]), key=lambda g: g[0])

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _labels in self._series})


def host_cpu_sampler() -> Callable[[], Optional[float]]:
    """Returns a closure yielding CPU utilisation in [0, 1] from /proc/stat
    deltas (None on the first call or on non-Linux hosts)."""
    prev: Dict[str, float] = {}

    def sample() -> Optional[float]:
        try:
            with open("/proc/stat") as f:
                fields = f.readline().split()
        except OSError:
            return None
        if not fields or fields[0] != "cpu":
            return None
        vals = [float(x) for x in fields[1:]]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)
        total = sum(vals)
        d_total = total - prev.get("total", 0.0)
        d_idle = idle - prev.get("idle", 0.0)
        first = not prev
        prev["total"], prev["idle"] = total, idle
        if first or d_total <= 0:
            return None
        return max(0.0, min(1.0, 1.0 - d_idle / d_total))

    return sample


def tpu_hbm_sampler() -> Callable[[], List[Tuple[str, float, float]]]:
    """Returns a closure yielding [(device_id, bytes_in_use, bytes_limit)]
    for local accelerator devices; empty on CPU-only hosts."""

    def sample() -> List[Tuple[str, float, float]]:
        try:
            import jax

            out = []
            for d in jax.local_devices():
                if d.platform == "cpu":
                    continue
                stats = getattr(d, "memory_stats", lambda: None)()
                if not stats:
                    continue
                out.append((
                    str(d.id),
                    float(stats.get("bytes_in_use", 0)),
                    float(stats.get("bytes_limit", 0)),
                ))
            return out
        except Exception:
            return []

    return sample


class MetricsCollector:
    """Background sampler: every ``interval_s`` copies registry metrics and
    host/TPU stats into the store. ``tick()`` is callable directly so tests
    and single-threaded callers can sample deterministically."""

    def __init__(
        self,
        store: TimeSeriesStore,
        registry: Optional[MetricsRegistry] = None,
        *,
        interval_s: float = 15.0,
        cpu_sample: Optional[Callable[[], Optional[float]]] = None,
        hbm_sample: Optional[Callable[[], List[Tuple[str, float, float]]]] = None,
    ):
        self.store = store
        self.registry = registry
        self.interval_s = interval_s
        self._cpu = cpu_sample if cpu_sample is not None else host_cpu_sampler()
        self._hbm = hbm_sample if hbm_sample is not None else tpu_hbm_sampler()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, now: Optional[float] = None) -> None:
        t = time.time() if now is None else now
        cpu = self._cpu()
        if cpu is not None:
            self.store.record("node_cpu_utilization", cpu, t=t)
        for dev, used, limit in self._hbm():
            labels = (("device", dev),)
            self.store.record("tpu_hbm_bytes_in_use", used, t=t, labels=labels)
            if limit > 0:
                self.store.record(
                    "tpu_hbm_utilization", used / limit, t=t, labels=labels
                )
        if self.registry is not None:
            for name, labels, v in self.registry.snapshot():
                self.store.record(name, v, t=t, labels=labels)

    def start(self) -> "MetricsCollector":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception as e:   # sampling must never kill the app
                    log.warning("metrics tick failed", kv={"err": repr(e)})

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()


class MetricsService:
    """The dashboard-facing query API (the reference MetricsService
    abstraction, metrics_service.ts:21-42)."""

    def __init__(self, store: TimeSeriesStore):
        self.store = store

    # Named accessors mirroring the reference's interface --------------

    def node_cpu_utilization(self, window_s: float = 600.0) -> List[Point]:
        return self.store.query("node_cpu_utilization", window_s)

    def tpu_hbm_utilization(self, window_s: float = 600.0) -> List[Point]:
        return self.store.query("tpu_hbm_utilization", window_s)

    def series(self, name: str, window_s: float = 600.0) -> List[Point]:
        return self.store.query(name, window_s)

    # HTTP --------------------------------------------------------------

    def router(self) -> Router:
        r = Router()

        def _list(q: Request):
            return {"series": self.store.names()}

        def _query(q: Request):
            try:
                window = float(q.query.get("window", "600"))
            except ValueError:
                raise RestError(400, "window must be a number of seconds")
            # Single store scan: the merged view is derived from the groups
            # so the two views can't disagree at the window edge.
            groups = self.store.query_groups(q.params["name"], window)
            pts = sorted(
                (p for _labels, gp in groups for p in gp),
                key=lambda p: p.t,
            )
            return {
                "series": q.params["name"],
                "points": [
                    {"t": p.t, "value": p.value, "labels": dict(p.labels)}
                    for p in pts
                ],
                "groups": [
                    {
                        "labels": dict(labels),
                        "points": [{"t": p.t, "value": p.value} for p in gp],
                    }
                    for labels, gp in groups
                ],
            }

        r.get("/api/metrics", _list)
        r.get("/api/metrics/<name>", _query)
        return r
